"""Nested host spans and the per-step StepTimeline.

`span("fwd")` is both a context manager and a decorator. Every span is
reported to two sinks:

- the active `profiler.Profiler` record window (cat ``observability``), so
  spans land on the same chrome-trace timeline as op dispatch events and
  `RecordEvent` annotations;
- the installed `StepTimeline` (if any), which stitches spans together with
  the other per-step signals the framework already produces but previously
  scattered across four log formats: observed host syncs
  (`framework.core` sync-observer chain), `comm_watchdog.comm_task`
  intervals, and eager dispatch-cache hit/miss/bypass deltas.

One `StepTimeline` record per training step is the unit the flight recorder
buffers and the JSONL exporter appends — see docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import threading
import time
from collections import deque

__all__ = [
    "span",
    "StepTimeline",
    "active_timeline",
    "enable_step_timeline",
    "disable_step_timeline",
    "publish_step_record",
    "fleet_step_summary",
    "overlap_stats",
    "record_span",
]

_tls = threading.local()


def _span_stack() -> list:
    stack = getattr(_tls, "spans", None)
    if stack is None:
        stack = _tls.spans = []
    return stack


class span:
    """`with span("fwd"): ...` or `@span("fwd")`. Nesting is tracked per
    thread; the reported name is the slash-joined path ("step/fwd/attn")."""

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs
        self._t0 = None
        self._path = None

    def __enter__(self):
        stack = _span_stack()
        self._path = "/".join([s._path for s in stack[-1:]] + [self.name]) \
            if stack else self.name
        stack.append(self)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        stack = _span_stack()
        depth = len(stack) - 1
        if stack and stack[-1] is self:
            stack.pop()
        _emit_span(self._path or self.name, self._t0, t1, depth, self.attrs)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with span(self.name, **self.attrs):
                return fn(*args, **kwargs)

        return wrapped


def record_span(name, t0_ns, t1_ns, **attrs):
    """Report an externally measured interval to the span sinks (profiler +
    StepTimeline) after the fact — for windows whose qualification is only
    known at their END (e.g. the input-h2d-behind-inflight-step compute
    credit, which must verify the device was STILL busy when the window
    closed before claiming overlap)."""
    _emit_span(name, t0_ns, t1_ns, len(_span_stack()), attrs)


def _emit_span(path, t0_ns, t1_ns, depth, attrs):
    # profiler sink: only while a record window is open
    from ..profiler import profiler as _prof_mod

    prof = _prof_mod._active_profiler
    if prof is not None and prof._recording:
        prof._add_event(path, t0_ns, t1_ns, cat="observability")
    tl = _active_timeline
    if tl is not None:
        tl._on_span(path, t0_ns, t1_ns, depth, attrs)


# --------------------------------------------------------------------------- #
# comm/compute overlap (interval-union math)
# --------------------------------------------------------------------------- #


def _merge_intervals(intervals):
    """[(start, end), ...] -> sorted disjoint union (zero/negative-length
    input intervals are dropped)."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    merged = []
    for s, e in ivs:
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return merged


def _union_len(merged):
    return sum(e - s for s, e in merged)


def _intersect_len(a, b):
    """Total length of the intersection of two DISJOINT-SORTED interval
    lists (two-pointer sweep — O(n+m), not pairwise)."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


# comm_task kinds whose intervals join the comm union of the overlap
# accounting; any other kind ("step", ...) is deadline tracking only
COMM_KINDS = ("comm", "a2a")


def overlap_stats(comm_tasks, spans) -> dict:
    """Per-step comm/compute overlap from a step record's interval lists.

    comm intervals: `comm_tasks` entries with a communication kind —
    "comm", or "a2a" (MoE dispatch/combine all-to-alls, ISSUE-14; eager
    a2a intervals are measured, compiled-path ones are `[est]`-marked
    analytic estimates registered via distributed/moe_comm.py).
    Deadline-only regions like the trainer's whole-step watchdog tag
    ("step") stay excluded.
    compute intervals: spans explicitly tagged `kind="compute"` — driver
    wrappers (fit/train_batch and friends) span the whole step including
    its comm, so compute attribution is opt-in, not inferred.

    `fraction` is the share of the comm interval UNION covered by the
    compute union (T3's tracked-overlap metric, host-observed); a zero-comm
    step reports 1.0 — nothing was exposed. `exposed_s` is the remainder,
    the direct target of the overlap scheduling work.
    """
    comm = _merge_intervals(
        (t.get("start_ns", 0) / 1e9,
         t.get("start_ns", 0) / 1e9 + t.get("dur_s", 0.0))
        for t in comm_tasks if t.get("kind", "comm") in COMM_KINDS)
    compute = _merge_intervals(
        (s.get("start_ns", 0) / 1e9,
         s.get("start_ns", 0) / 1e9 + s.get("dur_s", 0.0))
        for s in spans
        if (s.get("attrs") or {}).get("kind") == "compute")
    comm_s = _union_len(comm)
    covered = _intersect_len(comm, compute) if comm_s else 0.0
    fraction = covered / comm_s if comm_s > 0 else 1.0
    return {
        "fraction": round(min(fraction, 1.0), 6),
        "comm_s": round(comm_s, 6),
        "covered_s": round(covered, 6),
        "exposed_s": round(max(comm_s - covered, 0.0), 6),
    }


def aggregate_overlap(overlaps) -> dict:
    """Roll per-step `overlap` dicts into one: fraction = total covered /
    total comm, 1.0 when there was no comm at all. The ONE definition of
    the roll-up convention — bench.py, `fleet_step_summary`, and
    tools/overlap_report.py all aggregate through here."""
    overlaps = list(overlaps)
    comm = sum(o.get("comm_s", 0.0) for o in overlaps)
    covered = sum(o.get("covered_s", 0.0) for o in overlaps)
    return {
        "fraction": round(covered / comm, 6) if comm > 0 else 1.0,
        "comm_s": round(comm, 6),
        "covered_s": round(covered, 6),
        "exposed_s": round(max(comm - covered, 0.0), 6),
    }


# registry handles for the per-step overlap emission (HandleCache: survives
# reset_default_registry in tests)
_overlap_metrics = None


def _emit_overlap_metrics(ov):
    global _overlap_metrics
    if _overlap_metrics is None:
        from .metrics import HandleCache

        _overlap_metrics = HandleCache(lambda reg: (
            reg.gauge("step_overlap_fraction",
                      "comm interval time covered by concurrent compute "
                      "spans, last step"),
            reg.counter("comm_exposed_seconds_total",
                        "comm interval time NOT covered by compute spans"),
            reg.counter("comm_overlapped_seconds_total",
                        "comm interval time covered by compute spans"),
        ))
    frac, exposed, covered = _overlap_metrics.get()
    frac.set(ov["fraction"])
    if ov["exposed_s"]:
        exposed.inc(ov["exposed_s"])
    if ov["covered_s"]:
        covered.inc(ov["covered_s"])


def _autotune_snapshot():
    """Chosen Pallas kernel tiles + per-kernel autotune hit/miss/fallback
    counts, folded into each step record so BENCH rounds can attribute MFU
    movement to tile choices. sys.modules lookup, never an import — a run
    that launched no Pallas kernel pays nothing and records nothing."""
    mod = sys.modules.get("paddle_tpu.ops.pallas.autotune")
    if mod is None:
        return None
    return mod.chosen_tiles() or None


# --------------------------------------------------------------------------- #
# StepTimeline
# --------------------------------------------------------------------------- #

_active_timeline: "StepTimeline | None" = None


def active_timeline() -> "StepTimeline | None":
    return _active_timeline


class StepTimeline:
    """Stitch one structured record per training step.

    Install it (`enable_step_timeline()` or `.install()`), then have the
    step driver — `hapi.Model.fit`, `ResilientTrainer`, `bench.py
    --emit-metrics` — call `step_begin(i)` / `step_end()`. Everything else
    is collected passively through chained hooks:

    - host syncs via `framework.core.add_sync_observer` (composes with the
      graftlint runtime checks — neither clobbers the other);
    - `comm_task` intervals via `comm_watchdog.add_task_observer`;
    - spans via the module-level `span` sink;
    - dispatch-cache hit/miss/bypass deltas snapshotted at the step edges.

    Records land in a bounded deque (the flight recorder's source), and
    optionally as one JSON line per step in `jsonl_path`.
    """

    def __init__(self, jsonl_path: str | None = None, keep: int = 512,
                 max_spans_per_step: int = 256):
        self.jsonl_path = jsonl_path
        self.records: deque = deque(maxlen=keep)
        self.max_spans_per_step = max_spans_per_step
        self.interstep_syncs = 0
        self._installed = False
        self._cur = None  # in-progress step dict
        self._dropped_spans = 0
        # running total over CLOSED steps — the bounded ring evicts old
        # records, so summing it would undercount on runs longer than `keep`
        self._closed_step_syncs = 0

    # -- hook plumbing --------------------------------------------------- #

    def install(self) -> "StepTimeline":
        global _active_timeline
        if self._installed:
            return self
        from ..distributed import comm_watchdog
        from ..framework import core

        if _active_timeline is not None:
            _active_timeline.uninstall()
        core.add_sync_observer(self._on_sync)
        comm_watchdog.add_task_observer(self._on_comm_task)
        self._installed = True
        _active_timeline = self
        return self

    def uninstall(self):
        global _active_timeline
        if not self._installed:
            return
        from ..distributed import comm_watchdog
        from ..framework import core

        core.remove_sync_observer(self._on_sync)
        comm_watchdog.remove_task_observer(self._on_comm_task)
        self._installed = False
        if _active_timeline is self:
            _active_timeline = None

    # -- passive collectors ---------------------------------------------- #

    def _on_sync(self, kind, tensor):
        cur = self._cur
        if cur is None:
            self.interstep_syncs += 1
        else:
            cur["host_syncs"] += 1
            kinds = cur["sync_kinds"]
            kinds[kind] = kinds.get(kind, 0) + 1
        return None  # never replace the synced value

    def _on_comm_task(self, desc, t0_ns, t1_ns, kind="comm"):
        cur = self._cur
        if cur is not None:
            cur["comm_tasks"].append(
                {"desc": desc, "kind": kind,
                 "start_ns": t0_ns - cur["_t0_ns"],
                 "dur_s": round((t1_ns - t0_ns) / 1e9, 6)})

    def _on_span(self, path, t0_ns, t1_ns, depth, attrs):
        cur = self._cur
        if cur is None:
            return
        if len(cur["spans"]) >= self.max_spans_per_step:
            self._dropped_spans += 1
            return
        rec = {"name": path, "depth": depth,
               "start_ns": t0_ns - cur["_t0_ns"],
               "dur_s": round((t1_ns - t0_ns) / 1e9, 6)}
        if attrs:
            rec["attrs"] = dict(attrs)
        cur["spans"].append(rec)

    # -- step boundaries -------------------------------------------------- #

    def step_begin(self, step: int):
        if self._cur is not None:
            # driver skipped an end (exception path): close what we have
            self.step_end()
        from ..framework import core

        self._cur = {
            "step": int(step),
            "t_wall": time.time(),
            "_t0_ns": time.perf_counter_ns(),
            "host_syncs": 0,
            "sync_kinds": {},
            "comm_tasks": [],
            "spans": [],
            "_dispatch0": core.dispatch_cache_stats(),
        }

    def step_end(self, extra: dict | None = None) -> dict | None:
        cur, self._cur = self._cur, None
        if cur is None:
            return None
        from ..framework import core

        t1 = time.perf_counter_ns()
        d0 = cur.pop("_dispatch0")
        d1 = core.dispatch_cache_stats()
        overlap = overlap_stats(cur["comm_tasks"], cur["spans"])
        record = {
            "step": cur["step"],
            "t_wall": round(cur["t_wall"], 6),
            "dur_s": round((t1 - cur.pop("_t0_ns")) / 1e9, 6),
            "host_syncs": cur["host_syncs"],
            "sync_kinds": cur["sync_kinds"],
            "comm_tasks": cur["comm_tasks"],
            "spans": cur["spans"],
            "overlap": overlap,
            "overlap_fraction": overlap["fraction"],
            "dispatch": {k: d1[k] - d0[k]
                         for k in ("hits", "misses", "bypass")},
        }
        tiles = _autotune_snapshot()
        if tiles:
            record["autotune"] = tiles
        if extra:
            record.update(extra)
        _emit_overlap_metrics(overlap)
        self._closed_step_syncs += record["host_syncs"]
        self.records.append(record)
        if self.jsonl_path:
            # default=repr: span attrs / extra are user-fed (numpy scalars
            # included) and must never abort the training step over a
            # serialization TypeError
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(record, sort_keys=True, default=repr)
                        + "\n")
        from . import flight

        flight.feed_step(record)
        return record

    # -- reading ---------------------------------------------------------- #

    def total_host_syncs(self) -> int:
        """Every sync observed since install: closed steps + between-step +
        the in-progress step (the number the graftlint runtime report's
        `host_syncs_total` must agree with on the same run, even after the
        ring has evicted early records)."""
        n = self.interstep_syncs + self._closed_step_syncs
        if self._cur is not None:
            n += self._cur["host_syncs"]
        return n


def enable_step_timeline(jsonl_path: str | None = None, keep: int = 512
                         ) -> StepTimeline:
    """Create + install a StepTimeline (replacing any active one)."""
    return StepTimeline(jsonl_path=jsonl_path, keep=keep).install()


def disable_step_timeline():
    if _active_timeline is not None:
        _active_timeline.uninstall()


# --------------------------------------------------------------------------- #
# cross-rank aggregation over the rendezvous store
# --------------------------------------------------------------------------- #


def publish_step_record(store, rank: int, record: dict,
                        prefix: str = "telemetry"):
    """Every rank publishes its step record; any TCPStore-shaped object
    (set/get/tryget) works, including the fleet's rendezvous store."""
    store.set(f"{prefix}/step{record['step']}/rank{rank}",
              json.dumps(record, sort_keys=True, default=repr))


def fleet_step_summary(store, world_size: int, step: int,
                       prefix: str = "telemetry", timeout: float = 30.0
                       ) -> dict:
    """Rank 0 gathers every rank's record for `step` and reduces it to one
    fleet line: step-time spread (the straggler signal the TPU concurrency
    study attributes scaling losses to), total host syncs, total comm time."""
    recs = []
    deadline = time.monotonic() + timeout
    for r in range(world_size):
        key = f"{prefix}/step{step}/rank{r}"
        raw = None
        tryget = getattr(store, "tryget", None)
        while raw is None:
            if tryget is not None:
                raw = tryget(key)
            else:
                # get-only stores: poll through absent-key errors so the
                # deadline still applies. (A get() that BLOCKS internally
                # is outside this contract — TCPStore exposes tryget for
                # exactly this reason.)
                try:
                    raw = store.get(key)
                except (KeyError, RuntimeError):
                    raw = None  # absent key: retry until the deadline
            if raw is None:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"fleet_step_summary: rank {r} never published "
                        f"{key} within {timeout}s")
                time.sleep(0.02)
        recs.append(json.loads(raw))
    durs = [rec["dur_s"] for rec in recs]
    slowest = max(range(world_size), key=lambda i: durs[i])
    # overlap aggregate over ranks (records predating the overlap field
    # contribute zeros)
    fleet_overlap = aggregate_overlap(rec.get("overlap") or {}
                                      for rec in recs)
    return {
        "step": step,
        "ranks": world_size,
        "step_time_s": {
            "min": min(durs),
            "max": max(durs),
            "mean": sum(durs) / len(durs),
        },
        "straggler_rank": slowest,
        "host_syncs": sum(rec["host_syncs"] for rec in recs),
        "comm_task_s": round(sum(t["dur_s"] for rec in recs
                                 for t in rec["comm_tasks"]), 6),
        "overlap": fleet_overlap,
        "dispatch": {
            k: sum(rec["dispatch"][k] for rec in recs)
            for k in ("hits", "misses", "bypass")
        },
    }
