"""Unified telemetry layer: metrics registry, step timeline, flight recorder.

The framework already produces rich runtime signals — sync-observer and
op-input-interceptor hooks in `framework.core`, `dispatch_cache_stats()`,
comm-watchdog reports, elastic heartbeats, checkpoint commit events — but
until this subsystem they had no common place to be recorded, aggregated, or
exported. Three pieces (docs/OBSERVABILITY.md):

- `metrics` — process-wide counters/gauges/histograms with labels; lock-free
  emission, JSONL + Prometheus text exporters.
- `spans` — nested `span()` context/decorator feeding the profiler's
  chrome-trace AND the per-step `StepTimeline`, which stitches host spans,
  `comm_task` intervals, observed host syncs, and dispatch-cache deltas into
  one structured record per training step (cross-rank aggregation over the
  TCPStore via `fleet_step_summary`).
- `flight` — bounded ring of recent step records + metric deltas, dumped to
  a post-mortem file on crash, watchdog overrun, or SIGTERM.
"""

from . import flight, metrics, spans
from .flight import (
    FlightRecorder,
    get_recorder,
    install_crash_handlers,
    reset_recorder,
    uninstall_crash_handlers,
)
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    reset_default_registry,
)
from .spans import (
    StepTimeline,
    active_timeline,
    disable_step_timeline,
    enable_step_timeline,
    fleet_step_summary,
    overlap_stats,
    publish_step_record,
    span,
)

__all__ = [
    "metrics",
    "spans",
    "flight",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "reset_default_registry",
    "span",
    "StepTimeline",
    "active_timeline",
    "enable_step_timeline",
    "disable_step_timeline",
    "publish_step_record",
    "fleet_step_summary",
    "overlap_stats",
    "FlightRecorder",
    "get_recorder",
    "reset_recorder",
    "install_crash_handlers",
    "uninstall_crash_handlers",
]
