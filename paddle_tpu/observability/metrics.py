"""Process-wide metrics registry: counters, gauges, histograms with labels.

Design constraints (the fit loop and eager dispatch are hot paths):

- **No locks on the emit path.** A metric cell is a one-slot mutable box;
  `inc`/`set`/`observe` mutate it under the GIL only. The registry lock is
  taken solely when a *new* (metric, label-set) cell is created — steady-state
  emission is a dict lookup plus a float add.
- **Deferred aggregation.** Nothing is summarized at emit time; `collect()`,
  the exporters, and `snapshot()`/`delta()` walk the cells on demand
  (readers take no locks either: cells are only ever added, never removed,
  and a torn read of a float counter is an acceptable off-by-one in a
  monitoring sample, not a correctness bug).
- **Stdlib only.** This module must be importable from anywhere in the
  package (collective.py, hapi, the launcher) without cycles.

Exporters: `prometheus_text()` emits the Prometheus text exposition format;
`jsonl_events()` emits one JSON object per sample for append-only event logs
(the same shape the StepTimeline JSONL uses — see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Iterable, Sequence

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "HandleCache",
    "MetricsRegistry",
    "default_registry",
    "reset_default_registry",
    "DEFAULT_BUCKETS",
]

# latency-oriented default: 1ms .. ~2min, roughly x4 per bucket
DEFAULT_BUCKETS = (0.001, 0.004, 0.016, 0.064, 0.25, 1.0, 4.0, 16.0, 64.0, 128.0)


def _label_key(labelnames: Sequence[str], labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"metric labels {sorted(labels)} do not match declared "
            f"labelnames {sorted(labelnames)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Base: a named family of cells, one per label-value combination."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._cells: dict[tuple, list] = {}
        self._lock = threading.Lock()

    def _new_cell(self) -> list:
        raise NotImplementedError

    def _cell(self, labels: dict) -> list:
        key = _label_key(self.labelnames, labels)
        cell = self._cells.get(key)
        if cell is None:
            with self._lock:
                cell = self._cells.setdefault(key, self._new_cell())
        return cell

    def samples(self) -> Iterable[tuple[dict, object]]:
        """(labels dict, cell value view) per label combination."""
        for key, cell in list(self._cells.items()):
            yield dict(zip(self.labelnames, key)), cell


class Counter(_Metric):
    """Monotonic counter. `inc(amount, **labels)`."""

    kind = "counter"

    def _new_cell(self) -> list:
        return [0.0]

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        self._cell(labels)[0] += amount

    def value(self, **labels) -> float:
        return self._cell(labels)[0]


class Gauge(_Metric):
    """Point-in-time value. `set(v)`, `inc()`, `dec()`."""

    kind = "gauge"

    def _new_cell(self) -> list:
        return [0.0]

    def set(self, value: float, **labels):
        self._cell(labels)[0] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        self._cell(labels)[0] += amount

    def dec(self, amount: float = 1.0, **labels):
        self._cell(labels)[0] -= amount

    def value(self, **labels) -> float:
        return self._cell(labels)[0]


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): cell is
    [bucket_counts..., sum, count]; `le` boundaries are upper-inclusive."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_cell(self) -> list:
        # one count slot per finite bucket, then sum, then total count
        return [0] * len(self.buckets) + [0.0, 0]

    def observe(self, value: float, **labels):
        cell = self._cell(labels)
        i = bisect.bisect_left(self.buckets, value)
        if i < len(self.buckets):
            cell[i] += 1
        cell[-2] += value
        cell[-1] += 1

    def sum(self, **labels) -> float:
        return self._cell(labels)[-2]

    def count(self, **labels) -> int:
        return self._cell(labels)[-1]

    def mean(self, **labels) -> float:
        cell = self._cell(labels)
        return cell[-2] / cell[-1] if cell[-1] else 0.0


class MetricsRegistry:
    """Named metric families. Re-declaring a name returns the existing
    family (so call sites don't need import-order coordination) but a kind
    or labelname mismatch is an error, never a silent second family."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    # -- declaration ----------------------------------------------------- #

    def _declare(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames, **kw)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with labels "
                f"{m.labelnames}; cannot re-declare as {cls.kind} with "
                f"{tuple(labelnames)}")
        return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._declare(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._declare(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        m = self._declare(Histogram, name, help, labelnames, buckets=buckets)
        want = tuple(sorted(float(b) for b in buckets))
        if m.buckets != want:
            # same contract as kind/label mismatches: observations landing
            # in another caller's bucket layout must fail loudly
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}; cannot re-declare with {want}")
        return m

    def get(self, name) -> _Metric | None:
        return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    # -- reading --------------------------------------------------------- #

    def collect(self) -> list[dict]:
        """Flat sample list: one dict per (metric, label-set); histograms
        carry their bucket counts inline."""
        out = []
        for m in list(self._metrics.values()):
            for labels, cell in m.samples():
                s = {"metric": m.name, "type": m.kind, "labels": labels}
                if m.kind == "histogram":
                    s["sum"] = cell[-2]
                    s["count"] = cell[-1]
                    s["buckets"] = {
                        str(b): c for b, c in zip(m.buckets, cell[:-2])}
                else:
                    s["value"] = cell[0]
                out.append(s)
        return out

    def snapshot(self) -> dict:
        """Scalar view keyed "name{k=v,...}" — the input to `delta()` (the
        flight recorder stores one of these per dump window)."""
        snap = {}
        for s in self.collect():
            key = _format_series(s["metric"], s["labels"])
            snap[key] = s["count"] if s["type"] == "histogram" else s["value"]
        return snap

    def delta(self, since: dict) -> dict:
        """Per-series change vs an earlier `snapshot()`. Gauges report their
        current value, not a difference (a delta of a point-in-time reading
        is meaningless) — and are ALWAYS included, zero or not: a crash-dump
        reader must be able to tell "heartbeat age 0 (fresh)" from "gauge
        never set". Unchanged counters/histograms are elided."""
        out = {}
        for s in self.collect():
            key = _format_series(s["metric"], s["labels"])
            if s["type"] == "gauge":
                out[key] = s["value"]
                continue
            cur = s["count"] if s["type"] == "histogram" else s["value"]
            d = cur - since.get(key, 0)
            if d:
                out[key] = d
        return out

    # -- exporters ------------------------------------------------------- #

    def prometheus_text(self) -> str:
        """Prometheus text exposition format (one HELP/TYPE header per
        family, `_bucket`/`_sum`/`_count` expansion for histograms)."""
        lines = []
        for m in list(self._metrics.values()):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, cell in m.samples():
                if m.kind == "histogram":
                    acc = 0
                    for b, c in zip(m.buckets, cell[:-2]):
                        acc += c
                        lines.append(_prom_line(
                            f"{m.name}_bucket", {**labels, "le": _fmt_num(b)},
                            acc))
                    lines.append(_prom_line(
                        f"{m.name}_bucket", {**labels, "le": "+Inf"},
                        cell[-1]))
                    lines.append(_prom_line(f"{m.name}_sum", labels, cell[-2]))
                    lines.append(_prom_line(f"{m.name}_count", labels, cell[-1]))
                else:
                    lines.append(_prom_line(m.name, labels, cell[0]))
        return "\n".join(lines) + ("\n" if lines else "")

    def jsonl_events(self, ts: float | None = None) -> list[str]:
        """One JSON line per sample. `ts` pins the timestamp (tests use 0);
        default is the current wall clock."""
        if ts is None:
            ts = time.time()
        return [json.dumps({"ts": round(ts, 6), **s}, sort_keys=True)
                for s in self.collect()]

    def export_jsonl(self, path: str, ts: float | None = None):
        lines = self.jsonl_events(ts)
        if lines:
            with open(path, "a") as f:
                f.write("\n".join(lines) + "\n")


def _fmt_num(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _format_series(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def _prom_line(name: str, labels: dict, value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape(str(v))}"' for k, v in sorted(labels.items()))
        return f"{name}{{{inner}}} {_fmt_num(value)}"
    return f"{name} {_fmt_num(value)}"


def _escape(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


class HandleCache:
    """Registry-identity-keyed cache of metric handles for hot-path
    emitters: re-declaring through the registry lock on every emission is
    avoidable overhead, but a plain cached handle goes stale when
    `reset_default_registry()` swaps the registry (tests) — emissions would
    land in a dead registry. `build(reg)` runs once per registry instance;
    `get()` is a two-attribute read steady-state.

    The one shared implementation for collective.py, profiler/timer.py and
    ResilientTrainer — keep them on it so the invalidation rule can't
    diverge."""

    __slots__ = ("_build", "_cache")

    def __init__(self, build):
        self._build = build
        self._cache = None  # (registry, handles)

    def get(self):
        reg = default_registry()
        cache = self._cache
        if cache is None or cache[0] is not reg:
            cache = (reg, self._build(reg))
            self._cache = cache
        return cache[1]


_default: MetricsRegistry | None = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every built-in emitter uses."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MetricsRegistry()
    return _default


def reset_default_registry():
    """Drop every registered family (tests)."""
    global _default
    with _default_lock:
        _default = MetricsRegistry()
    return _default
