"""Crash flight recorder: the last N step timelines + recent metric deltas,
dumped to a post-mortem file when the process dies.

A hung or crashing TPU job can't be re-run with logging turned up — the
evidence has to already be in memory when it dies. The recorder keeps a
bounded ring of `StepTimeline` records (fed automatically while a timeline
is installed), a ring of annotated events (checkpoint commits, watchdog
overruns, elastic holds), and a metrics snapshot to diff against.

`dump()` writes one JSON document combining those with the non-destructive
`comm_watchdog.peek_report()` and the dispatch-cache counters. It is called
by `ResilientTrainer` on a step exception or watchdog overrun, by the
SIGTERM/excepthook handlers `install_crash_handlers()` chains in, and the
launcher points workers at a per-worker path via ``PADDLE_FLIGHT_FILE`` so
the post-mortem survives the pod teardown (folded into the worker log next
to the watchdog report spill — launch/main.py).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque

from . import metrics as metrics_mod

__all__ = [
    "FlightRecorder",
    "get_recorder",
    "reset_recorder",
    "feed_step",
    "install_crash_handlers",
    "uninstall_crash_handlers",
    "default_path",
]


def default_path() -> str:
    """PADDLE_FLIGHT_FILE (set per worker by the launcher) or a cwd file."""
    return os.environ.get("PADDLE_FLIGHT_FILE", "flight_recorder.json")


class FlightRecorder:
    def __init__(self, capacity: int = 64, event_capacity: int = 256,
                 registry: metrics_mod.MetricsRegistry | None = None):
        self.steps: deque = deque(maxlen=capacity)
        self.events: deque = deque(maxlen=event_capacity)
        self._registry = registry
        # reentrant: the SIGTERM handler runs on the main thread and may
        # interrupt a dump() already holding this lock (e.g. the watchdog-
        # overrun dump blocked in fsync) — a plain Lock would self-deadlock
        self._lock = threading.RLock()
        self._metrics_base: dict = {}
        self._dump_count = 0

    @property
    def registry(self) -> metrics_mod.MetricsRegistry:
        return self._registry or metrics_mod.default_registry()

    # -- feeding ---------------------------------------------------------- #

    def record_step(self, record: dict):
        self.steps.append(record)

    def note(self, kind: str, **fields):
        """Annotate the timeline (checkpoint save, watchdog overrun, hold)."""
        self.events.append({"t_wall": round(time.time(), 6),
                            "kind": kind, **fields})

    def snapshot_metrics(self):
        """Start a fresh delta window (dump() reports changes since here)."""
        self._metrics_base = self.registry.snapshot()

    # -- dumping ---------------------------------------------------------- #

    def postmortem(self, reason: str = "", lockfree: bool = False) -> dict:
        """`lockfree=True` is the SIGNAL-HANDLER mode: the handler runs on
        the main thread and may have interrupted code holding core's
        dispatch lock or the watchdog lock (both non-reentrant) — calling
        their collectors from the handler would self-deadlock, so they are
        skipped. The metrics registry and the rings are lock-free reads."""
        doc = {
            "reason": reason,
            "t_wall": round(time.time(), 6),
            "pid": os.getpid(),
            "rank": os.environ.get("PADDLE_TRAINER_ID"),
            "restart_count": os.environ.get("PADDLE_RESTART_COUNT"),
            "dump_count": self._dump_count,
            "steps": list(self.steps),
            "events": list(self.events),
            "metric_deltas": self.registry.delta(self._metrics_base),
            "metrics": self.registry.collect(),
        }
        if lockfree:
            doc["lockfree"] = True
            return doc
        from ..distributed import comm_watchdog
        from ..framework import core

        doc["dispatch_cache"] = core.dispatch_cache_stats()
        doc["watchdog_report"] = comm_watchdog.peek_report()
        doc["watchdog_timeouts"] = comm_watchdog.timeout_count()
        return doc

    def dump(self, path: str | None = None, reason: str = "",
             lockfree: bool = False) -> str:
        """Write the post-mortem JSON; returns the path. Append-safe: each
        dump is one JSON document per line, so a crash that follows a
        watchdog overrun keeps both records."""
        path = path or default_path()
        with self._lock:
            self._dump_count += 1
            doc = self.postmortem(reason, lockfree=lockfree)
            # default=repr: span attrs and note() fields are user-fed
            # (numpy scalars are the natural values) — a serialization
            # TypeError here would kill the dump at exactly the moment it
            # exists for, and mask the original crash
            text = json.dumps(doc, sort_keys=True, default=repr)
            try:
                with open(path, "a") as f:
                    f.write(text + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError as e:
                # the ring buffer is the only copy — stderr (→ worker log)
                # is the fallback channel, same stance as the watchdog spill
                print(f"[flight] post-mortem file {path} unwritable ({e}); "
                      f"dump follows:\n{text}",
                      file=sys.stderr, flush=True)
        return path


_default_recorder: FlightRecorder | None = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    global _default_recorder
    if _default_recorder is None:
        with _recorder_lock:
            if _default_recorder is None:
                _default_recorder = FlightRecorder()
    return _default_recorder


def reset_recorder() -> FlightRecorder:
    global _default_recorder
    with _recorder_lock:
        _default_recorder = FlightRecorder()
    return _default_recorder


def feed_step(record: dict):
    """StepTimeline sink: only an already-created recorder buffers steps
    (importing the timeline must not silently spin up crash machinery)."""
    rec = _default_recorder
    if rec is not None:
        rec.record_step(record)


# --------------------------------------------------------------------------- #
# crash handlers
# --------------------------------------------------------------------------- #

_handlers_installed = False
_prev_sigterm = None
_prev_excepthook = None


def install_crash_handlers(path: str | None = None):
    """Chain a SIGTERM handler and sys.excepthook that dump the default
    recorder before the previous behavior runs. Idempotent; main thread
    only for the signal part (a worker thread caller still gets the
    excepthook)."""
    global _handlers_installed, _prev_sigterm, _prev_excepthook
    if _handlers_installed:
        return
    dump_path = path

    def _on_sigterm(signum, frame):
        # lockfree: the interrupted main thread may hold the dispatch or
        # watchdog lock; those collectors are skipped in the signal path
        get_recorder().dump(dump_path, reason="SIGTERM", lockfree=True)
        prev = _prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev != signal.SIG_IGN:
            # default disposition: restore and re-raise so the exit code
            # still reads as signal death to the launcher. An explicitly
            # IGNORED SIGTERM stays ignored — dumping must not turn a
            # deliberate SIG_IGN into process death.
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def _on_except(exc_type, exc, tb):
        get_recorder().dump(
            dump_path, reason=f"uncaught {exc_type.__name__}: {exc}")
        (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    _prev_excepthook = sys.excepthook
    sys.excepthook = _on_except
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        # not the main thread: excepthook-only installation
        _prev_sigterm = None
    _handlers_installed = True


def uninstall_crash_handlers():
    global _handlers_installed, _prev_sigterm, _prev_excepthook
    if not _handlers_installed:
        return
    if _prev_excepthook is not None:
        sys.excepthook = _prev_excepthook
        _prev_excepthook = None
    if _prev_sigterm is not None:
        try:
            signal.signal(signal.SIGTERM, _prev_sigterm)
        except ValueError:
            pass
        _prev_sigterm = None
    _handlers_installed = False
