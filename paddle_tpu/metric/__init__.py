"""Metrics (reference: python/paddle/metric/metrics.py)."""

from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x.numpy()) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name or "acc"
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        idx = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = idx == l[..., None]
        return correct

    def update(self, correct, *args):
        c = _np(correct)
        accs = []
        for i, k in enumerate(self.topk):
            num = c[..., :k].sum(-1).astype(np.float64)
            self.total[i] += num.sum()
            self.count[i] += num.size
            accs.append(num.mean())
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = _np(preds)
        l = _np(labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, 1]
        bins = np.round(p * self.num_thresholds).astype(np.int64)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        area = 0.0
        pos = neg = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2
            pos, neg = new_pos, new_neg
        return area / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    import jax.numpy as jnp

    from ..framework.core import run_op, to_tensor

    def fn(p, l):
        idx = jnp.argsort(-p, axis=-1)[..., :k]
        ll = l if l.ndim == p.ndim - 1 else l[..., 0]
        c = jnp.any(idx == ll[..., None], axis=-1)
        return jnp.mean(c.astype(jnp.float32))

    t_in = input if isinstance(input, Tensor) else to_tensor(input)
    t_l = label if isinstance(label, Tensor) else to_tensor(label)
    return run_op("accuracy", fn, [t_in, t_l])
