"""Vision transforms on numpy arrays (reference: python/paddle/vision/transforms/).
Transforms run on host (CPU) in DataLoader workers; tensors stay numpy until
device dispatch."""

from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        out = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img.astype(np.float32)
        if self.data_format == "CHW":
            out = out.transpose(2, 0, 1)
        return out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


def _target_hw(img, size):
    if isinstance(size, numbers.Number):
        h, w = img.shape[:2]
        if h < w:
            return int(size), int(size * w / h)
        return int(size * h / w), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img, size, interpolation="bilinear"):
    """Host resize without PIL: nearest or bilinear."""
    nh, nw = _target_hw(img, size)
    h, w = img.shape[:2]
    if interpolation == "nearest" or (nh == h and nw == w):
        ri = (np.arange(nh) * h / nh).astype(np.int64).clip(0, h - 1)
        ci = (np.arange(nw) * w / nw).astype(np.int64).clip(0, w - 1)
        return img[ri][:, ci]
    # bilinear, align_corners=False convention
    src = img.astype(np.float32)
    ry = (np.arange(nh) + 0.5) * h / nh - 0.5
    rx = (np.arange(nw) + 0.5) * w / nw - 0.5
    y0 = np.floor(ry).astype(np.int64)
    x0 = np.floor(rx).astype(np.int64)
    wy = (ry - y0)[:, None]
    wx = (rx - x0)[None, :]
    y0c = y0.clip(0, h - 1)
    y1c = (y0 + 1).clip(0, h - 1)
    x0c = x0.clip(0, w - 1)
    x1c = (x0 + 1).clip(0, w - 1)
    if src.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = src[y0c][:, x0c] * (1 - wx) + src[y0c][:, x1c] * wx
    bot = src[y1c][:, x0c] * (1 - wx) + src[y1c][:, x1c] * wx
    out = top * (1 - wy) + bot * wy
    if img.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return _resize_np(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            p = self.padding
            img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2), mode="constant")
        h, w = img.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[::-1].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if isinstance(p, int):
            pads = [(p, p), (p, p)]
        else:
            pads = [(p[1], p[3]), (p[0], p[2])] if len(p) == 4 else [(p[1], p[1]), (p[0], p[0])]
        pads += [(0, 0)] * (img.ndim - 2)
        return np.pad(img, pads, mode="constant", constant_values=self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_np(img[i:i + th, j:j + tw], self.size, self.interpolation)
        return _resize_np(img, self.size, self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


# --------------------------------------------------------------------------- #
# functional tail (reference: python/paddle/vision/transforms/functional.py)
# --------------------------------------------------------------------------- #


def _is_chw(img):
    return img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] > 4


def _clip_like(out, ref):
    """Warp resampling: preserve the image's own range (normalized float
    images legitimately hold negative values)."""
    if ref.dtype == np.uint8:
        return np.clip(np.round(out), 0, 255.0).astype(np.uint8)
    return out.astype(ref.dtype)


def _clip_color(out, ref):
    """Color adjustments: intensities stay non-negative for floats as well
    (matches the pre-round-5 Brightness/ContrastTransform clipping)."""
    if ref.dtype == np.uint8:
        return np.clip(np.round(out), 0, 255.0).astype(np.uint8)
    return np.clip(out, 0, None).astype(ref.dtype)


def adjust_brightness(img, factor):
    return _clip_color(img.astype(np.float32) * factor, img)


def adjust_contrast(img, factor):
    f = img.astype(np.float32)
    mean = to_grayscale(img).astype(np.float32).mean()
    return _clip_color((f - mean) * factor + mean, img)


def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma (reference functional.to_grayscale). HWC in."""
    f = img.astype(np.float32)
    if img.ndim == 2:
        g = f
    else:
        g = f[..., 0] * 0.299 + f[..., 1] * 0.587 + f[..., 2] * 0.114
    g = g[..., None]
    if num_output_channels == 3:
        g = np.repeat(g, 3, axis=-1)
    return g.astype(img.dtype) if img.dtype == np.uint8 else g


def adjust_saturation(img, factor):
    f = img.astype(np.float32)
    gray = to_grayscale(img, 3).astype(np.float32)
    return _clip_color(gray + (f - gray) * factor, img)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) through HSV."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    if img.ndim == 2 or img.shape[-1] == 1:
        return img  # hue is undefined on grayscale (torchvision behavior)
    f = img.astype(np.float32) / (255.0 if img.dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    mx, mn = f[..., :3].max(-1), f[..., :3].min(-1)
    diff = mx - mn + 1e-12
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6.0
    h = (h + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    i = np.floor(h * 6).astype(np.int32) % 6
    frac = h * 6 - np.floor(h * 6)
    p = v * (1 - s)
    q = v * (1 - frac * s)
    t = v * (1 - (1 - frac) * s)
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], -1)
    if img.dtype == np.uint8:
        out = out * 255.0
    return _clip_color(out, img)


def _warp(img, inv33, fill=0.0, perspective=False, method="bilinear",
          out_hw=None):
    """Inverse-map warp with bilinear or nearest sampling; img HWC (or HW).
    out_hw sets the output canvas size (rotate(expand=True))."""
    squeeze = img.ndim == 2
    if squeeze:
        img = img[..., None]
    H, W, C = img.shape
    Ho, Wo = out_hw if out_hw is not None else (H, W)
    ys, xs = np.meshgrid(np.arange(Ho, dtype=np.float64),
                         np.arange(Wo, dtype=np.float64), indexing="ij")
    ones = np.ones_like(xs)
    src = inv33 @ np.stack([xs.ravel(), ys.ravel(), ones.ravel()])
    if perspective:
        sx = src[0] / (src[2] + 1e-12)
        sy = src[1] / (src[2] + 1e-12)
    else:
        sx, sy = src[0], src[1]
    sx = sx.reshape(Ho, Wo)
    sy = sy.reshape(Ho, Wo)
    if method == "nearest":
        sx = np.floor(sx + 0.5)
        sy = np.floor(sy + 0.5)
    x0 = np.floor(sx).astype(np.int64)
    y0 = np.floor(sy).astype(np.int64)
    wx = (sx - x0)[..., None]
    wy = (sy - y0)[..., None]
    valid = (sx >= -1) & (sx <= W) & (sy >= -1) & (sy <= H)

    def tap(yy, xx):
        inside = (xx >= 0) & (xx < W) & (yy >= 0) & (yy < H)
        v = img[np.clip(yy, 0, H - 1), np.clip(xx, 0, W - 1)].astype(np.float64)
        return np.where(inside[..., None], v, fill)

    out = ((1 - wx) * (1 - wy) * tap(y0, x0)
           + wx * (1 - wy) * tap(y0, x0 + 1)
           + (1 - wx) * wy * tap(y0 + 1, x0)
           + wx * wy * tap(y0 + 1, x0 + 1))
    out = np.where(valid[..., None], out, fill)
    out = _clip_like(out, img)
    return out[..., 0] if squeeze else out


def _affine_inv_matrix(center, angle, translate, scale, shear):
    """Inverse of the paddle affine matrix (center-rotate-shear-scale +
    translate; reference functional.affine)."""
    cx, cy = center
    # positive angle = counter-clockwise on screen (torchvision/paddle
    # convention); with image y pointing down that is a negative math angle
    rot = np.deg2rad(-angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward: T(translate) . C . R(rot) . Shear . Scale . C^-1
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a * scale, b * scale, 0.0],
                  [c * scale, d * scale, 0.0],
                  [0.0, 0.0, 1.0]])
    pre = np.array([[1, 0, cx + translate[0]],
                    [0, 1, cy + translate[1]],
                    [0, 0, 1]], dtype=np.float64)
    post = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], dtype=np.float64)
    fwd = pre @ m @ post
    return np.linalg.inv(fwd)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="bilinear", fill=0, center=None):
    if np.isscalar(shear):
        shear = (float(shear), 0.0)
    H, W = img.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    inv = _affine_inv_matrix(center, angle, translate, scale, shear)
    return _warp(img, inv, fill=fill, method=interpolation)


def rotate(img, angle, interpolation="bilinear", expand=False, center=None,
           fill=0):
    if not expand:
        return affine(img, angle=angle, center=center, fill=fill,
                      interpolation=interpolation)
    H, W = img.shape[:2]
    rad = np.deg2rad(angle)
    ca, sa = abs(np.cos(rad)), abs(np.sin(rad))
    # round, not ceil: cos(90deg) is ~6e-17, and ceil would grow the canvas
    # by a spurious pixel on exact right-angle rotations
    Wo = int(np.floor(W * ca + H * sa + 0.5))
    Ho = int(np.floor(W * sa + H * ca + 0.5))
    cin = ((W - 1) * 0.5, (H - 1) * 0.5) if center is None else center
    cout = ((Wo - 1) * 0.5, (Ho - 1) * 0.5)
    # inverse map: recentre output, rotate back (y-down => +angle), shift in
    r = np.deg2rad(angle)
    rinv = np.array([[np.cos(r), -np.sin(r), 0],
                     [np.sin(r), np.cos(r), 0],
                     [0, 0, 1]], dtype=np.float64)
    t_in = np.array([[1, 0, cin[0]], [0, 1, cin[1]], [0, 0, 1]], np.float64)
    t_out = np.array([[1, 0, -cout[0]], [0, 1, -cout[1]], [0, 0, 1]],
                     np.float64)
    inv = t_in @ rinv @ t_out
    return _warp(img, inv, fill=fill, method=interpolation, out_hw=(Ho, Wo))


def _homography(src_pts, dst_pts):
    """3x3 mapping src->dst from 4 point pairs (least squares)."""
    A, bv = [], []
    for (x, y), (u, v) in zip(src_pts, dst_pts):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        bv.append(u)
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bv.append(v)
    h = np.linalg.lstsq(np.asarray(A, np.float64),
                        np.asarray(bv, np.float64), rcond=None)[0]
    return np.append(h, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="bilinear", fill=0):
    """Warp so that startpoints map to endpoints (reference
    functional.perspective)."""
    fwd = _homography(startpoints, endpoints)
    return _warp(img, np.linalg.inv(fwd), fill=fill, perspective=True,
                 method=interpolation)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase region [i:i+h, j:j+w] with value v (reference functional.erase).
    Accepts HWC or CHW."""
    out = img if inplace else img.copy()
    if _is_chw(out):
        vv = np.asarray(v)
        if vv.ndim == 1:  # per-channel fill must broadcast along C, not w
            vv = vv.reshape(-1, 1, 1)
        out[:, i:i + h, j:j + w] = vv
    else:
        out[i:i + h, j:j + w] = v
    return out


# --------------------------------------------------------------------------- #
# transform classes (reference: python/paddle/vision/transforms/transforms.py)
# --------------------------------------------------------------------------- #


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Randomly jitter brightness/contrast/saturation/hue in random order
    (reference transforms.ColorJitter)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0, hue=0.0):
        self.transforms = []
        if brightness:
            self.transforms.append(BrightnessTransform(brightness))
        if contrast:
            self.transforms.append(ContrastTransform(contrast))
        if saturation:
            self.transforms.append(SaturationTransform(saturation))
        if hue:
            self.transforms.append(HueTransform(hue))

    def _apply_image(self, img):
        for idx in np.random.permutation(len(self.transforms)):
            img = self.transforms[idx](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if np.isscalar(degrees):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.center = center
        self.fill = fill
        self.interpolation = interpolation
        self.expand = expand

    def _apply_image(self, img):
        a = np.random.uniform(*self.degrees)
        return rotate(img, a, center=self.center, fill=self.fill,
                      interpolation=self.interpolation, expand=self.expand)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        if np.isscalar(degrees):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.fill = fill
        self.center = center
        self.interpolation = interpolation

    def _apply_image(self, img):
        H, W = img.shape[:2]
        a = np.random.uniform(*self.degrees)
        t = (0.0, 0.0)
        if self.translate is not None:
            t = (np.random.uniform(-self.translate[0], self.translate[0]) * W,
                 np.random.uniform(-self.translate[1], self.translate[1]) * H)
        s = 1.0
        if self.scale is not None:
            s = np.random.uniform(*self.scale)
        sh = (0.0, 0.0)
        if self.shear is not None:
            shear = self.shear
            if np.isscalar(shear):
                shear = (-abs(shear), abs(shear))
            if len(shear) == 2:
                sh = (np.random.uniform(*shear), 0.0)
            else:
                sh = (np.random.uniform(shear[0], shear[1]),
                      np.random.uniform(shear[2], shear[3]))
        return affine(img, angle=a, translate=t, scale=s, shear=sh,
                      fill=self.fill, center=self.center,
                      interpolation=self.interpolation)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill
        self.interpolation = interpolation

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        H, W = img.shape[:2]
        d = self.distortion_scale
        dx, dy = int(d * W / 2), int(d * H / 2)
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [(np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)),
               (W - 1 - np.random.randint(0, dx + 1),
                np.random.randint(0, dy + 1)),
               (W - 1 - np.random.randint(0, dx + 1),
                H - 1 - np.random.randint(0, dy + 1)),
               (np.random.randint(0, dx + 1),
                H - 1 - np.random.randint(0, dy + 1))]
        return perspective(img, start, end, fill=self.fill,
                           interpolation=self.interpolation)


class RandomErasing(BaseTransform):
    """Erase a random rectangle (reference transforms.RandomErasing; Zhong
    et al. 2017). Works on HWC or CHW arrays."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return img
        chw = _is_chw(img)
        H, W = (img.shape[1], img.shape[2]) if chw else img.shape[:2]
        area = H * W
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            h = int(round(np.sqrt(target * ar)))
            w = int(round(np.sqrt(target / ar)))
            if h < H and w < W and h > 0 and w > 0:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                v = (np.random.standard_normal(
                        ((img.shape[0],) if chw else (img.shape[-1],))
                    ).astype(np.float32) if self.value == "random"
                    else self.value)
                return erase(img, i, j, h, w, v, inplace=self.inplace)
        return img


__all__ += [
    "SaturationTransform", "HueTransform", "ColorJitter", "Grayscale",
    "RandomRotation", "RandomAffine", "RandomPerspective", "RandomErasing",
    "adjust_brightness", "adjust_contrast", "adjust_saturation", "adjust_hue",
    "to_grayscale", "affine", "rotate", "perspective", "erase",
]
