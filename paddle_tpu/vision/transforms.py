"""Vision transforms on numpy arrays (reference: python/paddle/vision/transforms/).
Transforms run on host (CPU) in DataLoader workers; tensors stay numpy until
device dispatch."""

from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "RandomCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        out = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img.astype(np.float32)
        if self.data_format == "CHW":
            out = out.transpose(2, 0, 1)
        return out


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = img.astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


def _target_hw(img, size):
    if isinstance(size, numbers.Number):
        h, w = img.shape[:2]
        if h < w:
            return int(size), int(size * w / h)
        return int(size * h / w), int(size)
    return int(size[0]), int(size[1])


def _resize_np(img, size, interpolation="bilinear"):
    """Host resize without PIL: nearest or bilinear."""
    nh, nw = _target_hw(img, size)
    h, w = img.shape[:2]
    if interpolation == "nearest" or (nh == h and nw == w):
        ri = (np.arange(nh) * h / nh).astype(np.int64).clip(0, h - 1)
        ci = (np.arange(nw) * w / nw).astype(np.int64).clip(0, w - 1)
        return img[ri][:, ci]
    # bilinear, align_corners=False convention
    src = img.astype(np.float32)
    ry = (np.arange(nh) + 0.5) * h / nh - 0.5
    rx = (np.arange(nw) + 0.5) * w / nw - 0.5
    y0 = np.floor(ry).astype(np.int64)
    x0 = np.floor(rx).astype(np.int64)
    wy = (ry - y0)[:, None]
    wx = (rx - x0)[None, :]
    y0c = y0.clip(0, h - 1)
    y1c = (y0 + 1).clip(0, h - 1)
    x0c = x0.clip(0, w - 1)
    x1c = (x0 + 1).clip(0, w - 1)
    if src.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    top = src[y0c][:, x0c] * (1 - wx) + src[y0c][:, x1c] * wx
    bot = src[y1c][:, x0c] * (1 - wx) + src[y1c][:, x1c] * wx
    out = top * (1 - wy) + bot * wy
    if img.dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return _resize_np(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        if self.padding:
            p = self.padding
            img = np.pad(img, [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2), mode="constant")
        h, w = img.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        h, w = img.shape[:2]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[::-1].copy()
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        p = self.padding
        if isinstance(p, int):
            pads = [(p, p), (p, p)]
        else:
            pads = [(p[1], p[3]), (p[0], p[2])] if len(p) == 4 else [(p[1], p[1]), (p[0], p[0])]
        pads += [(0, 0)] * (img.ndim - 2)
        return np.pad(img, pads, mode="constant", constant_values=self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_np(img[i:i + th, j:j + tw], self.size, self.interpolation)
        return _resize_np(img, self.size, self.interpolation)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img.astype(np.float32) * f, 0, 255 if img.dtype == np.uint8 else None).astype(img.dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        f = 1 + np.random.uniform(-self.value, self.value)
        mean = img.mean()
        return np.clip((img.astype(np.float32) - mean) * f + mean, 0, 255 if img.dtype == np.uint8 else None).astype(img.dtype)
