"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: no downloads. MNIST/Cifar load from local files when
`data_file`/`image_path` is given; FakeData generates synthetic samples for
pipelines and benchmarks.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ..io import Dataset

__all__ = ["FakeData", "MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder"]


class FakeData(Dataset):
    """Synthetic image classification dataset."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        label = rng.randint(0, self.num_classes)
        if self.transform:
            img = self.transform(img)
        return img, np.int32(label)


class MNIST(Dataset):
    """reference: python/paddle/vision/datasets/mnist.py — reads the IDX
    format from local files (no download)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or not os.path.exists(image_path or "")):
            raise RuntimeError("downloads unavailable (zero-egress); pass image_path/label_path")
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        with gzip.open(image_path, "rb") if image_path.endswith(".gz") else open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") if label_path.endswith(".gz") else open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8)
        return images, labels

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform:
            img = self.transform(img)
        return img, np.int64(self.labels[idx])


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """reference: python/paddle/vision/datasets/cifar.py — local pickle batches."""

    def __init__(self, data_file=None, mode="train", transform=None, download=False, backend=None):
        if download and (data_file is None or not os.path.exists(data_file or "")):
            raise RuntimeError("downloads unavailable (zero-egress); pass data_file")
        self.transform = transform
        with open(data_file, "rb") as f:
            batch = pickle.load(f, encoding="bytes")
        self.data = batch[b"data"].reshape(-1, 3, 32, 32)
        self.labels = batch.get(b"labels", batch.get(b"fine_labels"))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform:
            img = self.transform(img.transpose(1, 2, 0))
        return img, np.int64(self.labels[idx])


class Cifar100(Cifar10):
    pass


class DatasetFolder(Dataset):
    """Image-folder dataset; uses raw numpy loading for .npy, defers other
    formats to a user loader."""

    def __init__(self, root, loader=None, extensions=(".npy",), transform=None):
        self.root = root
        self.loader = loader or (lambda p: np.load(p))
        self.transform = transform
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.int64(label)
