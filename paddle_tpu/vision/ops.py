"""Detection / vision operators (reference: python/paddle/vision/ops.py).

TPU design split:

* Shape-STATIC ops (roi_align, roi_pool, psroi_pool, box_coder, prior_box,
  yolo_box, yolo_loss, deform_conv2d) run as single jnp programs through
  run_op — bilinear sampling becomes vectorized gathers, deformable conv
  becomes sampled-im2col + one MXU matmul, exactly the layout XLA tiles
  well. The reference's CUDA kernels (deformable_conv_kernel.cu,
  roi_align_kernel.cu, yolo_box_op.cu) have no other residue here.
* Data-DEPENDENT-shape ops (nms, matrix_nms, generate_proposals,
  distribute_fpn_proposals) return variable-length results; XLA requires
  static shapes, so these run host-side on NumPy — matching how detection
  post-processing deploys in practice. Scores/boxes are device arrays right
  up to the final suppression pass.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, to_tensor
from ..nn.layer.layers import Layer
from ..nn.layer.container import Sequential

__all__ = [
    "yolo_loss",
    "yolo_box",
    "prior_box",
    "box_coder",
    "deform_conv2d",
    "DeformConv2D",
    "distribute_fpn_proposals",
    "read_file",
    "decode_jpeg",
    "psroi_pool",
    "PSRoIPool",
    "roi_pool",
    "RoIPool",
    "roi_align",
    "RoIAlign",
    "ConvNormActivation",
    "nms",
    "matrix_nms",
    "generate_proposals",
]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


# --------------------------------------------------------------------------- #
# box utilities
# --------------------------------------------------------------------------- #

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference ops.py:584; kernel
    phi/kernels/gpu/box_coder.cu)."""
    norm = 0.0 if box_normalized else 1.0

    if code_type == "encode_center_size":
        def fn(pb, tb, pbv=None):
            pw = pb[:, 2] - pb[:, 0] + norm
            ph = pb[:, 3] - pb[:, 1] + norm
            px = pb[:, 0] + pw * 0.5
            py = pb[:, 1] + ph * 0.5
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tx = tb[:, 0] + tw * 0.5
            ty = tb[:, 1] + th * 0.5
            # [T, P] broadcast: every target against every prior
            ox = (tx[:, None] - px[None, :]) / pw[None, :]
            oy = (ty[:, None] - py[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)
            if pbv is not None:
                v = pbv if pbv.ndim == 2 else jnp.broadcast_to(
                    pbv, (pb.shape[0], 4))
                out = out / v[None, :, :]
            return out

        if isinstance(prior_box_var, (list, tuple)):
            pbv = jnp.asarray(prior_box_var, jnp.float32)
            return run_op("box_coder_enc",
                          lambda pb, tb: fn(pb, tb, pbv),
                          [prior_box, target_box])
        if prior_box_var is None:
            return run_op("box_coder_enc", fn, [prior_box, target_box])
        return run_op("box_coder_enc",
                      lambda pb, tb, v: fn(pb, tb, v),
                      [prior_box, target_box, prior_box_var])

    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")

    def dec(pb, tb, pbv=None):
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        px = pb[:, 0] + pw * 0.5
        py = pb[:, 1] + ph * 0.5
        # tb: [N, M, 4]; priors broadcast along `axis`
        if axis == 0:
            pw_, ph_, px_, py_ = (a[None, :] for a in (pw, ph, px, py))
        else:
            pw_, ph_, px_, py_ = (a[:, None] for a in (pw, ph, px, py))
        t = tb
        if pbv is not None:
            v = pbv if pbv.ndim == 2 else jnp.broadcast_to(
                pbv, (pb.shape[0], 4))
            v = v[None, :, :] if axis == 0 else v[:, None, :]
            t = t * v
        ox = t[..., 0] * pw_ + px_
        oy = t[..., 1] * ph_ + py_
        ow = jnp.exp(t[..., 2]) * pw_
        oh = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                          ox + ow * 0.5 - norm, oy + oh * 0.5 - norm],
                         axis=-1)

    if isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
        return run_op("box_coder_dec", lambda pb, tb: dec(pb, tb, pbv),
                      [prior_box, target_box])
    if prior_box_var is None:
        return run_op("box_coder_dec", dec, [prior_box, target_box])
    return run_op("box_coder_dec", lambda pb, tb, v: dec(pb, tb, v),
                  [prior_box, target_box, prior_box_var])


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (reference ops.py:438). Returns (boxes [H,W,P,4],
    variances [H,W,P,4])."""
    H, W = int(input.shape[2]), int(input.shape[3])
    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or img_w / W
    step_h = steps[1] or img_h / H

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []  # (w, h) per prior, reference kernel ordering
    for ms in min_sizes:
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = float(max_sizes[min_sizes.index(ms)] if isinstance(
                    min_sizes, list) else max_sizes[0])
                s = np.sqrt(ms * mx)
                whs.append((s, s))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = float(max_sizes[list(min_sizes).index(ms)])
                s = np.sqrt(ms * mx)
                whs.append((s, s))
    whs = np.asarray(whs, np.float32)  # [P, 2]
    P = whs.shape[0]

    cx = (np.arange(W, dtype=np.float32) + offset) * step_w
    cy = (np.arange(H, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)  # [H, W]
    boxes = np.empty((H, W, P, 4), np.float32)
    boxes[..., 0] = (cxg[..., None] - whs[None, None, :, 0] / 2) / img_w
    boxes[..., 1] = (cyg[..., None] - whs[None, None, :, 1] / 2) / img_h
    boxes[..., 2] = (cxg[..., None] + whs[None, None, :, 0] / 2) / img_w
    boxes[..., 3] = (cyg[..., None] + whs[None, None, :, 1] / 2) / img_h
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          (H, W, P, 4)).copy()
    return to_tensor(boxes), to_tensor(var)


# --------------------------------------------------------------------------- #
# RoI ops — vectorized bilinear gathers (static shapes)
# --------------------------------------------------------------------------- #

def _rois_to_batch_index(boxes_num, n_rois):
    bn = _np(boxes_num).astype(np.int64)
    idx = np.repeat(np.arange(len(bn)), bn)
    if idx.shape[0] != n_rois:
        raise ValueError(
            f"boxes_num sums to {idx.shape[0]} but boxes has {n_rois} rows")
    return jnp.asarray(idx)


def _bilinear_sample(feat, y, x):
    """feat [C, H, W]; y/x broadcastable index grids -> sampled values
    [C, *grid] with zero padding outside."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def gather(yi, xi):
        yi_c = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
        xi_c = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
        v = feat[:, yi_c, xi_c]
        ok = ((yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1))
        return v * ok.astype(feat.dtype)

    return (gather(y0, x0) * (wy0 * wx0)
            + gather(y0, x1) * (wy0 * wx1)
            + gather(y1, x0) * (wy1 * wx0)
            + gather(y1, x1) * (wy1 * wx1))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference ops.py:1705; kernel roi_align_kernel.cu). One
    vmap over rois; each roi is a bilinear gather grid."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2
    batch_idx = _rois_to_batch_index(boxes_num, int(boxes.shape[0]))

    def fn(xv, bv):
        off = 0.5 if aligned else 0.0

        def one(roi, bi):
            x1, y1, x2, y2 = (roi * spatial_scale - off)
            rw = x2 - x1
            rh = y2 - y1
            if not aligned:
                rw = jnp.maximum(rw, 1.0)
                rh = jnp.maximum(rh, 1.0)
            bh = rh / ph
            bw = rw / pw
            iy = (jnp.arange(ph)[:, None, None, None]
                  * bh + y1 + (jnp.arange(sr)[None, None, :, None] + 0.5)
                  * bh / sr)
            ix = (jnp.arange(pw)[None, :, None, None]
                  * bw + x1 + (jnp.arange(sr)[None, None, None, :] + 0.5)
                  * bw / sr)
            iy = jnp.broadcast_to(iy, (ph, pw, sr, sr))
            ix = jnp.broadcast_to(ix, (ph, pw, sr, sr))
            vals = _bilinear_sample(xv[bi], iy, ix)  # [C, ph, pw, sr, sr]
            return vals.mean(axis=(-1, -2))

        return jax.vmap(one)(bv, batch_idx)

    return run_op("roi_align", fn, [x, boxes])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool max-pool variant (reference ops.py:1572)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    batch_idx = _rois_to_batch_index(boxes_num, int(boxes.shape[0]))

    def fn(xv, bv):
        H, W = xv.shape[-2], xv.shape[-1]

        def one(roi, bi):
            x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            feat = xv[bi]
            out = jnp.full((xv.shape[1], ph, pw), -jnp.inf, xv.dtype)
            # bin index of every pixel; scatter-max per bin
            by = jnp.clip(((ys - y1) * ph) // rh, 0, ph - 1)
            bx = jnp.clip(((xs - x1) * pw) // rw, 0, pw - 1)
            in_y = (ys >= y1) & (ys <= y2)
            in_x = (xs >= x1) & (xs <= x2)
            mask = in_y[:, None] & in_x[None, :]
            vals = jnp.where(mask[None], feat, -jnp.inf)
            flat_bin = by[:, None] * pw + bx[None, :]
            out = jax.ops.segment_max(
                vals.reshape(vals.shape[0], -1).T, flat_bin.reshape(-1),
                num_segments=ph * pw)  # [ph*pw, C]
            out = jnp.where(jnp.isfinite(out), out, 0.0)
            return out.T.reshape(xv.shape[1], ph, pw)

        return jax.vmap(one)(bv, batch_idx)

    return run_op("roi_pool", fn, [x, boxes])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pool (reference ops.py:1441). Channel
    dim must be C = out_c * ph * pw; bin (i,j) reads channel slice
    out_c*(i*pw+j)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    C = int(x.shape[1])
    if C % (ph * pw) != 0:
        raise ValueError(
            f"psroi_pool: input channels {C} not divisible by "
            f"{ph}*{pw}")
    out_c = C // (ph * pw)
    batch_idx = _rois_to_batch_index(boxes_num, int(boxes.shape[0]))

    def fn(xv, bv):
        H, W = xv.shape[-2], xv.shape[-1]

        def one(roi, bi):
            x1 = roi[0] * spatial_scale
            y1 = roi[1] * spatial_scale
            x2 = roi[2] * spatial_scale
            y2 = roi[3] * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1)
            rw = jnp.maximum(x2 - x1, 0.1)
            bh, bw = rh / ph, rw / pw
            feat = xv[bi].reshape(ph * pw, out_c, H, W)
            ys = jnp.arange(H, dtype=xv.dtype) + 0.5
            xs = jnp.arange(W, dtype=xv.dtype) + 0.5

            def bin_val(b):
                i, j = b // pw, b % pw
                y_lo, y_hi = y1 + i * bh, y1 + (i + 1) * bh
                x_lo, x_hi = x1 + j * bw, x1 + (j + 1) * bw
                m = ((ys[:, None] >= y_lo) & (ys[:, None] < y_hi)
                     & (xs[None, :] >= x_lo) & (xs[None, :] < x_hi))
                m = m.astype(xv.dtype)
                denom = jnp.maximum(m.sum(), 1.0)
                return (feat[b] * m[None]).sum(axis=(-1, -2)) / denom

            vals = jax.vmap(bin_val)(jnp.arange(ph * pw))  # [ph*pw, out_c]
            return vals.T.reshape(out_c, ph, pw)

        return jax.vmap(one)(bv, batch_idx)

    return run_op("psroi_pool", fn, [x, boxes])


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale, aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size,
                          self._spatial_scale)


# --------------------------------------------------------------------------- #
# deformable convolution — sampled-im2col + one MXU matmul
# --------------------------------------------------------------------------- #

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference ops.py:766; kernel
    deformable_conv_kernel.cu). Each output location bilinearly samples its
    kh*kw receptive field at learned offsets; samples form an im2col matrix
    contracted against the weights in ONE matmul — the MXU does the work,
    the gathers are the only irregular part."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    C_in = int(x.shape[1])
    use_mask = mask is not None

    def fn(xv, ov, wv, *rest):
        mv = rest[0] if use_mask else None
        bv = rest[-1] if (len(rest) == 2 or (len(rest) == 1 and not use_mask)) else None
        B, C, H, W = xv.shape
        out_h = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        out_w = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        # base sampling grid [out_h, out_w, kh, kw]
        oy = jnp.arange(out_h) * s[0] - p[0]
        ox = jnp.arange(out_w) * s[1] - p[1]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        # offsets: [B, 2*dg*kh*kw, out_h, out_w] (y then x per pair)
        off = ov.reshape(B, deformable_groups, kh * kw, 2, out_h, out_w)
        off_y = off[:, :, :, 0].transpose(0, 1, 3, 4, 2).reshape(
            B, deformable_groups, out_h, out_w, kh, kw)
        off_x = off[:, :, :, 1].transpose(0, 1, 3, 4, 2).reshape(
            B, deformable_groups, out_h, out_w, kh, kw)
        cg = C // deformable_groups

        def sample_batch(feat, offy, offx, m):
            # feat [C, H, W]; offy/offx [dg, out_h, out_w, kh, kw]
            def per_dg(f, oy_, ox_):
                yy = base_y + oy_
                xx = base_x + ox_
                return _bilinear_sample(f, yy, xx)  # [cg, oh, ow, kh, kw]

            cols = jax.vmap(per_dg)(
                feat.reshape(deformable_groups, cg, H, W), offy, offx)
            if m is not None:
                # v2 modulation mask: [dg*kh*kw, oh, ow] -> per-dg scale
                mm = m.reshape(deformable_groups, kh, kw, out_h, out_w) \
                    .transpose(0, 3, 4, 1, 2)  # [dg, oh, ow, kh, kw]
                cols = cols * mm[:, None]
            return cols.reshape(C, out_h, out_w, kh, kw)

        if use_mask:
            cols = jax.vmap(sample_batch)(xv, off_y, off_x, mv)
        else:
            cols = jax.vmap(lambda f, a, b: sample_batch(f, a, b, None))(
                xv, off_y, off_x)
        # cols [B, C, oh, ow, kh, kw] -> matmul with weight [O, C/g, kh, kw]
        O = wv.shape[0]
        cpg = C // groups
        opg = O // groups
        cols_g = cols.reshape(B, groups, cpg, out_h, out_w, kh, kw)
        w_g = wv.reshape(groups, opg, cpg, kh, kw)
        out = jnp.einsum("bgchwyx,gocyx->bgohw", cols_g, w_g)
        out = out.reshape(B, O, out_h, out_w)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    ins = [x, offset, weight]
    if use_mask:
        ins.append(mask)
    if bias is not None:
        ins.append(bias)
    return run_op("deform_conv2d", fn, ins)


class DeformConv2D(Layer):
    """reference ops.py:973."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        import math

        from ..nn import initializer as I

        fan_in = in_channels * ks[0] * ks[1] // groups
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr, default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             self._stride, self._padding, self._dilation,
                             self._deformable_groups, self._groups, mask)


# --------------------------------------------------------------------------- #
# YOLO
# --------------------------------------------------------------------------- #

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head to boxes+scores (reference ops.py:277; kernel
    yolo_box_op.cu). Returns (boxes [B,H*W*A,4], scores [B,H*W*A,C])."""
    anchors = list(anchors)
    na = len(anchors) // 2
    an = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))

    def fn(xv, img):
        B, _, H, W = xv.shape
        if iou_aware:
            ioup = jax.nn.sigmoid(xv[:, :na].reshape(B, na, 1, H, W))
            xv = xv[:, na:]
        v = xv.reshape(B, na, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=xv.dtype)
        gy = jnp.arange(H, dtype=xv.dtype)
        bx = ((jax.nn.sigmoid(v[:, :, 0]) - 0.5) * scale_x_y + 0.5
              + gx[None, None, None, :]) / W
        by = ((jax.nn.sigmoid(v[:, :, 1]) - 0.5) * scale_x_y + 0.5
              + gy[None, None, :, None]) / H
        input_size = downsample_ratio * H
        bw = jnp.exp(v[:, :, 2]) * an[None, :, 0, None, None] / input_size
        bh = jnp.exp(v[:, :, 3]) * an[None, :, 1, None, None] / input_size
        conf = jax.nn.sigmoid(v[:, :, 4])
        if iou_aware:
            conf = conf ** (1 - iou_aware_factor) \
                * ioup[:, :, 0] ** iou_aware_factor
        probs = jax.nn.sigmoid(v[:, :, 5:]) * conf[:, :, None]
        keep = (conf >= conf_thresh).astype(xv.dtype)
        img_h = img[:, 0].astype(xv.dtype)
        img_w = img[:, 1].astype(xv.dtype)
        x1 = (bx - bw / 2) * img_w[:, None, None, None]
        y1 = (by - bh / 2) * img_h[:, None, None, None]
        x2 = (bx + bw / 2) * img_w[:, None, None, None]
        y2 = (by + bh / 2) * img_h[:, None, None, None]
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w[:, None, None, None] - 1)
            y1 = jnp.clip(y1, 0, img_h[:, None, None, None] - 1)
            x2 = jnp.clip(x2, 0, img_w[:, None, None, None] - 1)
            y2 = jnp.clip(y2, 0, img_h[:, None, None, None] - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) \
            * keep[..., None]
        scores = probs * keep[:, :, None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(B, -1, 4)
        scores = scores.transpose(0, 1, 3, 4, 2).reshape(
            B, na * H * W, class_num)
        return boxes, scores

    return run_op("yolo_box", fn, [x, img_size])


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference ops.py:69; kernel yolov3_loss).
    Per-image loss: coordinate SSE (gt-assigned cells) + objectness BCE
    with ignore mask + class BCE."""
    anchors = list(anchors)
    anchor_mask = list(anchor_mask)
    na = len(anchor_mask)
    all_an = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_an = jnp.asarray(all_an[anchor_mask])

    def fn(xv, gb, gl, *rest):
        gs = rest[0] if gt_score is not None else None
        B, _, H, W = xv.shape
        input_size = downsample_ratio * H
        v = xv.reshape(B, na, 5 + class_num, H, W)
        px = jax.nn.sigmoid(v[:, :, 0])
        py = jax.nn.sigmoid(v[:, :, 1])
        pw_ = v[:, :, 2]
        ph_ = v[:, :, 3]
        obj_logit = v[:, :, 4]
        cls_logit = v[:, :, 5:]

        # decode predicted boxes (normalized) for the ignore mask
        gx = jnp.arange(W, dtype=xv.dtype)
        gy = jnp.arange(H, dtype=xv.dtype)
        bx = (px + gx[None, None, None, :]) / W
        by = (py + gy[None, None, :, None]) / H
        bw = jnp.exp(pw_) * mask_an[None, :, 0, None, None] / input_size
        bh = jnp.exp(ph_) * mask_an[None, :, 1, None, None] / input_size

        def iou_xywh(b1, b2):
            b1x1, b1x2 = b1[..., 0] - b1[..., 2] / 2, b1[..., 0] + b1[..., 2] / 2
            b1y1, b1y2 = b1[..., 1] - b1[..., 3] / 2, b1[..., 1] + b1[..., 3] / 2
            b2x1, b2x2 = b2[..., 0] - b2[..., 2] / 2, b2[..., 0] + b2[..., 2] / 2
            b2y1, b2y2 = b2[..., 1] - b2[..., 3] / 2, b2[..., 1] + b2[..., 3] / 2
            ix = jnp.maximum(jnp.minimum(b1x2, b2x2)
                             - jnp.maximum(b1x1, b2x1), 0)
            iy = jnp.maximum(jnp.minimum(b1y2, b2y2)
                             - jnp.maximum(b1y1, b2y1), 0)
            inter = ix * iy
            a1 = (b1x2 - b1x1) * (b1y2 - b1y1)
            a2 = (b2x2 - b2x1) * (b2y2 - b2y1)
            return inter / jnp.maximum(a1 + a2 - inter, 1e-10)

        pred = jnp.stack([bx, by, bw, bh], axis=-1)  # [B,na,H,W,4]
        # best IoU of each prediction vs any gt of its image
        ious = iou_xywh(pred[:, :, :, :, None, :],
                        gb[:, None, None, None, :, :])  # [B,na,H,W,G]
        best = ious.max(axis=-1)
        ignore = (best > ignore_thresh).astype(xv.dtype)

        # gt assignment: gt g -> cell (gi, gj), best anchor by wh IoU
        G = gb.shape[1]
        gwh = gb[..., 2:4]  # normalized
        an_n = jnp.asarray(all_an) / input_size  # [A, 2]
        inter = (jnp.minimum(gwh[:, :, None, 0], an_n[None, None, :, 0])
                 * jnp.minimum(gwh[:, :, None, 1], an_n[None, None, :, 1]))
        union = (gwh[:, :, 0:1] * gwh[:, :, 1:2]
                 + an_n[None, None, :, 0] * an_n[None, None, :, 1] - inter)
        an_iou = inter / jnp.maximum(union, 1e-10)
        best_an = an_iou.argmax(-1)  # [B, G] index into ALL anchors
        # map to this head's slot (or -1)
        slot = jnp.full_like(best_an, -1)
        for s_i, a_i in enumerate(anchor_mask):
            slot = jnp.where(best_an == a_i, s_i, slot)
        valid = (gwh[..., 0] > 0) & (slot >= 0)
        gi = jnp.clip((gb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        tx = gb[..., 0] * W - gi
        ty = gb[..., 1] * H - gj
        tw = jnp.log(jnp.maximum(
            gwh[..., 0] * input_size
            / jnp.maximum(jnp.asarray(all_an)[best_an][..., 0], 1e-10),
            1e-10))
        th = jnp.log(jnp.maximum(
            gwh[..., 1] * input_size
            / jnp.maximum(jnp.asarray(all_an)[best_an][..., 1], 1e-10),
            1e-10))
        score = gs if gs is not None else jnp.ones(gb.shape[:2], xv.dtype)
        wgt = (2.0 - gwh[..., 0] * gwh[..., 1]) * score \
            * valid.astype(xv.dtype)

        bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, G))
        slot_c = jnp.clip(slot, 0, na - 1)

        def at(pred_map):
            return pred_map[bidx, slot_c, gj, gi]

        def bce(logit, label):
            return jax.nn.softplus(logit) - logit * label

        loss_xy = (bce(v[:, :, 0][bidx, slot_c, gj, gi], tx)
                   + bce(v[:, :, 1][bidx, slot_c, gj, gi], ty)) * wgt
        loss_wh = (jnp.abs(at(pw_) - tw) + jnp.abs(at(ph_) - th)) * wgt
        # objectness: positives at gt cells, negatives elsewhere not ignored
        obj_pos = jnp.zeros((B, na, H, W), xv.dtype)
        obj_pos = obj_pos.at[bidx, slot_c, gj, gi].max(
            valid.astype(xv.dtype) * score)
        noobj = (1.0 - (obj_pos > 0)) * (1.0 - ignore)
        loss_obj = (bce(obj_logit, jnp.ones_like(obj_logit)) * obj_pos
                    + bce(obj_logit, jnp.zeros_like(obj_logit)) * noobj)
        smooth = 1.0 / max(class_num, 1) if (use_label_smooth
                                             and class_num > 1) else 0.0
        tcls = jax.nn.one_hot(gl, class_num, dtype=xv.dtype)
        tcls = tcls * (1.0 - smooth) + smooth / 2.0
        cls_at = cls_logit.transpose(0, 1, 3, 4, 2)[bidx, slot_c, gj, gi]
        loss_cls = (bce(cls_at, tcls).sum(-1)) * valid.astype(xv.dtype) \
            * score
        # all four terms reduce to a per-image [B] loss
        return (loss_xy.sum(1) + loss_wh.sum(1)
                + loss_obj.sum(axis=(1, 2, 3)) + loss_cls.sum(1))

    ins = [x, gt_box, gt_label]
    if gt_score is not None:
        ins.append(gt_score)
    return run_op("yolo_loss", fn, ins)


# --------------------------------------------------------------------------- #
# NMS family — host-side (data-dependent output shapes)
# --------------------------------------------------------------------------- #

def _iou_matrix(boxes):
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    ix = np.maximum(np.minimum(x2[:, None], x2[None]) -
                    np.maximum(x1[:, None], x1[None]), 0)
    iy = np.maximum(np.minimum(y2[:, None], y2[None]) -
                    np.maximum(y1[:, None], y1[None]), 0)
    inter = ix * iy
    return inter / np.maximum(area[:, None] + area[None] - inter, 1e-10)


def _nms_np(boxes, scores, iou_threshold):
    order = np.argsort(-scores)
    iou = _iou_matrix(boxes)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = False
    return np.asarray(keep, np.int64)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS (reference ops.py:1934). Host-side: variable-length output.
    Returns kept indices sorted by score."""
    b = _np(boxes).astype(np.float64)
    if scores is None:
        s = np.arange(len(b), 0, -1, dtype=np.float64)
    else:
        s = _np(scores).astype(np.float64)
    if category_idxs is None:
        keep = _nms_np(b, s, iou_threshold)
    else:
        cat = _np(category_idxs)
        keep_all = []
        for c in categories:
            idx = np.nonzero(cat == c)[0]
            if idx.size == 0:
                continue
            k = _nms_np(b[idx], s[idx], iou_threshold)
            keep_all.append(idx[k])
        keep = np.concatenate(keep_all) if keep_all else np.empty(0, np.int64)
        keep = keep[np.argsort(-s[keep], kind="stable")]
    if top_k is not None:
        keep = keep[:top_k]
    return to_tensor(keep.astype(np.int64))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference ops.py:2358, SOLOv2). Host-side decay-based
    suppression. Returns (out [N,6], rois_num?, index?)."""
    bb = _np(bboxes).astype(np.float64)   # [B, M, 4]
    sc = _np(scores).astype(np.float64)   # [B, C, M]
    B, C, M = sc.shape
    outs, idxs, nums = [], [], []
    for bi in range(B):
        dets, det_idx = [], []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[bi, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            sel = sel[np.argsort(-s[sel])][:nms_top_k]
            boxes_c = bb[bi, sel]
            s_c = s[sel]
            iou = _iou_matrix(boxes_c)
            n = len(sel)
            decay = np.ones(n)
            iou_u = np.triu(iou, 1)
            max_iou = iou_u.max(axis=0) if n > 1 else np.zeros(n)
            for j in range(n):
                ious_j = iou_u[:j, j]
                if ious_j.size == 0:
                    continue
                if use_gaussian:
                    d = np.exp(-(ious_j ** 2 - max_iou[:j] ** 2)
                               / gaussian_sigma).min()
                else:
                    d = ((1 - ious_j) / np.maximum(1 - max_iou[:j],
                                                   1e-10)).min()
                decay[j] = d
            new_s = s_c * decay
            ok = new_s > post_threshold
            for j in np.nonzero(ok)[0]:
                dets.append([c, new_s[j], *boxes_c[j]])
                det_idx.append(bi * M + sel[j])
        dets = np.asarray(dets, np.float64).reshape(-1, 6)
        det_idx = np.asarray(det_idx, np.int64)
        if len(dets) > keep_top_k:
            order = np.argsort(-dets[:, 1])[:keep_top_k]
            dets, det_idx = dets[order], det_idx[order]
        else:
            order = np.argsort(-dets[:, 1]) if len(dets) else np.empty(0, int)
            dets, det_idx = dets[order], det_idx[order]
        outs.append(dets)
        idxs.append(det_idx)
        nums.append(len(dets))
    out = to_tensor(np.concatenate(outs).astype(np.float32)
                    if outs else np.zeros((0, 6), np.float32))
    res = [out]
    if return_rois_num:
        res.append(to_tensor(np.asarray(nums, np.int32)))
    if return_index:
        res.append(to_tensor(np.concatenate(idxs)
                             if idxs else np.empty(0, np.int64)))
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference ops.py:2106). Decode on device,
    filter+NMS on host."""
    sc = _np(scores)          # [B, A, H, W]
    bd = _np(bbox_deltas)     # [B, A*4, H, W]
    ims = _np(img_size)       # [B, 2]
    an = _np(anchors).reshape(-1, 4)   # [H*W*A, 4]
    vr = _np(variances).reshape(-1, 4)
    B = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0
    rois, roi_probs, nums = [], [], []
    for bi in range(B):
        s = sc[bi].transpose(1, 2, 0).reshape(-1)
        d = bd[bi].reshape(-1, 4, sc.shape[2], sc.shape[3]) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, a, v = s[order], d[order], an[order], vr[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        ax = a[:, 0] + aw / 2
        ay = a[:, 1] + ah / 2
        cx = v[:, 0] * d[:, 0] * aw + ax
        cy = v[:, 1] * d[:, 1] * ah + ay
        w = np.exp(np.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        h = np.exp(np.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], 1)
        H_img, W_img = ims[bi, 0], ims[bi, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, W_img - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, H_img - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        ok = (ws >= min_size) & (hs >= min_size)
        boxes, s = boxes[ok], s[ok]
        keep = _nms_np(boxes, s, nms_thresh)[:post_nms_top_n]
        rois.append(boxes[keep])
        roi_probs.append(s[keep])
        nums.append(len(keep))
    out_rois = to_tensor(np.concatenate(rois).astype(np.float32))
    out_probs = to_tensor(np.concatenate(roi_probs).astype(np.float32))
    if return_rois_num:
        return out_rois, out_probs, to_tensor(np.asarray(nums, np.int32))
    return out_rois, out_probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels by scale (reference ops.py:1175)."""
    rois = _np(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-10))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    n_levels = max_level - min_level + 1
    multi_rois, restore = [], np.empty(len(rois), np.int64)
    rois_num_per = []
    pos = 0
    for li in range(n_levels):
        idx = np.nonzero(lvl == min_level + li)[0]
        multi_rois.append(to_tensor(rois[idx].astype(np.float32)))
        restore[idx] = np.arange(pos, pos + len(idx))
        pos += len(idx)
        rois_num_per.append(len(idx))
    restore_ind = to_tensor(restore.reshape(-1, 1))
    if rois_num is not None:
        rn = _np(rois_num)
        starts = np.concatenate([[0], np.cumsum(rn)])
        per_level_nums = []
        for li in range(n_levels):
            cnt = np.zeros(len(rn), np.int32)
            for bi in range(len(rn)):
                seg = lvl[starts[bi]:starts[bi + 1]]
                cnt[bi] = int((seg == min_level + li).sum())
            per_level_nums.append(to_tensor(cnt))
        return multi_rois, restore_ind, per_level_nums
    return multi_rois, restore_ind


# --------------------------------------------------------------------------- #
# file IO
# --------------------------------------------------------------------------- #

def read_file(filename, name=None):
    """Raw file bytes as uint8 tensor (reference ops.py:1345)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return to_tensor(data)


def decode_jpeg(x, mode="unchanged", name=None):
    """JPEG decode (reference ops.py:1388). Host-side via Pillow when
    available; this environment has no GPU nvjpeg analog."""
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "decode_jpeg requires Pillow for host-side decoding") from e
    import io

    img = Image.open(io.BytesIO(_np(x).tobytes()))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return to_tensor(arr)


class ConvNormActivation(Sequential):
    """Conv2D + Norm + Activation block (reference ops.py:1877)."""

    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, norm_layer=None,
                 activation_layer=None, dilation=1, bias=None):
        from .. import nn as pnn

        if padding is None:
            padding = (kernel_size - 1) // 2 * dilation
        if norm_layer is None:
            norm_layer = pnn.BatchNorm2D
        if activation_layer is None:
            activation_layer = pnn.ReLU
        if bias is None:
            bias = norm_layer is None
        layers = [pnn.Conv2D(in_channels, out_channels, kernel_size, stride,
                             padding, dilation=dilation, groups=groups,
                             bias_attr=None if bias else False)]
        if norm_layer is not None:
            layers.append(norm_layer(out_channels))
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)
