"""DenseNet (reference API: python/paddle/vision/models/densenet.py;
architecture from Huang et al. 2017 — dense blocks with feature concat)."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(dropout)

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)
        self.relu = nn.ReLU()
        self.pool = nn.AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        assert layers in _CFG, f"layers must be one of {sorted(_CFG)}"
        init_ch, growth, block_cfg = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_ch, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_ch), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        blocks = []
        ch = init_ch
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _make(layers):
    def build(pretrained=False, **kwargs):
        assert not pretrained, "pretrained weights are not bundled"
        return DenseNet(layers=layers, **kwargs)

    build.__name__ = f"densenet{layers}"
    return build


densenet121 = _make(121)
densenet161 = _make(161)
densenet169 = _make(169)
densenet201 = _make(201)
densenet264 = _make(264)
