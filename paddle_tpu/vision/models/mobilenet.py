"""MobileNet V1/V2/V3 (reference API: python/paddle/vision/models/
mobilenetv1.py MobileNetV1 :66, mobilenetv2.py MobileNetV2 :83,
mobilenetv3.py MobileNetV3Small/Large :300+; architectures per the papers,
built on paddle_tpu.nn)."""

from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = [
    "MobileNetV1", "mobilenet_v1",
    "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v3_small", "mobilenet_v3_large",
]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


# --------------------------------------------------------------------------- #
# V1 (reference mobilenetv1.py:66 — depthwise separable stacks)
# --------------------------------------------------------------------------- #


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.dw = _ConvBNAct(in_c, in_c, 3, stride=stride, groups=in_c)
        self.pw = _ConvBNAct(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    """reference mobilenetv1.py:66."""

    _CFG = [  # (out_c, stride)
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
        (1024, 2), (1024, 1),
    ]

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        c = int(32 * scale)
        layers = [_ConvBNAct(3, c, 3, stride=2)]
        for out_c, stride in self._CFG:
            oc = int(out_c * scale)
            layers.append(_DepthwiseSeparable(c, oc, stride))
            c = oc
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    """reference mobilenetv1.py mobilenet_v1."""
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV1(scale=scale, **kwargs)


# --------------------------------------------------------------------------- #
# V2 (reference mobilenetv2.py:83 — inverted residuals)
# --------------------------------------------------------------------------- #


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNAct(in_c, hidden, 1, act=nn.ReLU6))
        layers.append(_ConvBNAct(hidden, hidden, 3, stride=stride,
                                 groups=hidden, act=nn.ReLU6))
        layers.append(_ConvBNAct(hidden, out_c, 1, act=None))
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """reference mobilenetv2.py:83."""

    _CFG = [  # t, c, n, s
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
    ]

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNAct(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in self._CFG:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNAct(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    """reference mobilenetv2.py mobilenet_v2."""
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV2(scale=scale, **kwargs)


# --------------------------------------------------------------------------- #
# V3 (reference mobilenetv3.py — SE blocks + hardswish)
# --------------------------------------------------------------------------- #


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, squeeze_c):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(c, squeeze_c, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_c, c, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp_c != in_c:
            layers.append(_ConvBNAct(in_c, exp_c, 1, act=act))
        layers.append(_ConvBNAct(exp_c, exp_c, k, stride=stride,
                                 groups=exp_c, act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp_c, _make_divisible(exp_c // 4)))
        layers.append(_ConvBNAct(exp_c, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# k, exp, out, SE, act, stride  (reference mobilenetv3.py config tables)
_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1),
    (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1),
    (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1),
    (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2),
    (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1),
    (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2),
    (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]
_V3_SMALL = [
    (3, 16, 16, True, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1),
    (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1),
    (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2),
    (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [_ConvBNAct(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out_c, se, act, s in cfg:
            layers.append(_V3Block(
                in_c, _make_divisible(exp * scale),
                _make_divisible(out_c * scale), k, s, se, act))
            in_c = _make_divisible(out_c * scale)
        exp_c = _make_divisible(last_exp * scale)
        layers.append(_ConvBNAct(in_c, exp_c, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        last_c = _make_divisible(1280 * scale) if last_exp == 960 else \
            _make_divisible(1024 * scale)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(exp_c, last_c),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(last_c, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    """reference mobilenetv3.py MobileNetV3Large."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    """reference mobilenetv3.py MobileNetV3Small."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    """reference mobilenetv3.py mobilenet_v3_small."""
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    """reference mobilenetv3.py mobilenet_v3_large."""
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)
