"""Shared conv building blocks for the vision zoo."""

from __future__ import annotations

import paddle_tpu.nn as nn

__all__ = ["ConvBNReLU"]


class ConvBNReLU(nn.Layer):
    """Conv2D (no bias) + BatchNorm2D + ReLU — the stem/branch unit shared
    by GoogLeNet and InceptionV3."""

    def __init__(self, in_ch, out_ch, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_ch, out_ch, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_ch)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))
