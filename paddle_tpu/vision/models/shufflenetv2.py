"""ShuffleNetV2 (reference API: python/paddle/vision/models/shufflenetv2.py;
architecture from Ma et al. 2018 — channel split + shuffle)."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_REPEATS = (4, 8, 4)


def _act(name):
    if name not in ("relu", "swish"):
        raise ValueError(f"act must be 'relu' or 'swish', got {name!r}")
    return nn.Swish() if name == "swish" else nn.ReLU()


def _channel_shuffle(x, groups):
    B, C, H, W = x.shape
    x = x.reshape([B, groups, C // groups, H, W])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([B, C, H, W])


class _ShuffleUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, concat + shuffle."""

    def __init__(self, ch, act):
        super().__init__()
        branch = ch // 2
        self.branch = nn.Sequential(
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
            nn.Conv2D(branch, branch, 3, padding=1, groups=branch,
                      bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )

    def forward(self, x):
        half = x.shape[1] // 2
        x1, x2 = x[:, :half], x[:, half:]
        out = paddle.concat([x1, self.branch(x2)], axis=1)
        return _channel_shuffle(out, 2)


class _ShuffleUnitDown(nn.Layer):
    """stride-2 unit: both branches downsample, channels double."""

    def __init__(self, in_ch, out_ch, act):
        super().__init__()
        branch = out_ch // 2
        self.branch1 = nn.Sequential(
            nn.Conv2D(in_ch, in_ch, 3, stride=2, padding=1, groups=in_ch,
                      bias_attr=False),
            nn.BatchNorm2D(in_ch),
            nn.Conv2D(in_ch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )
        self.branch2 = nn.Sequential(
            nn.Conv2D(in_ch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
            nn.Conv2D(branch, branch, 3, stride=2, padding=1, groups=branch,
                      bias_attr=False),
            nn.BatchNorm2D(branch),
            nn.Conv2D(branch, branch, 1, bias_attr=False),
            nn.BatchNorm2D(branch), _act(act),
        )

    def forward(self, x):
        out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        assert scale in _STAGE_OUT, f"scale must be one of {sorted(_STAGE_OUT)}"
        chs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, chs[0], 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(chs[0]), _act(act),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        stages = []
        in_ch = chs[0]
        for stage_i, repeats in enumerate(_REPEATS):
            out_ch = chs[stage_i + 1]
            stages.append(_ShuffleUnitDown(in_ch, out_ch, act))
            for _ in range(repeats - 1):
                stages.append(_ShuffleUnit(out_ch, act))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv_last = nn.Sequential(
            nn.Conv2D(in_ch, chs[-1], 1, bias_attr=False),
            nn.BatchNorm2D(chs[-1]), _act(act),
        )
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _make(scale, act="relu", tag=None):
    def build(pretrained=False, **kwargs):
        assert not pretrained, "pretrained weights are not bundled"
        return ShuffleNetV2(scale=scale, act=act, **kwargs)

    build.__name__ = tag or f"shufflenet_v2_x{str(scale).replace('.', '_')}"
    return build


shufflenet_v2_x0_25 = _make(0.25)
shufflenet_v2_x0_33 = _make(0.33)
shufflenet_v2_x0_5 = _make(0.5)
shufflenet_v2_x1_0 = _make(1.0)
shufflenet_v2_x1_5 = _make(1.5)
shufflenet_v2_x2_0 = _make(2.0)
shufflenet_v2_swish = _make(1.0, act="swish", tag="shufflenet_v2_swish")
