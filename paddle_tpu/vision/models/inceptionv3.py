"""Inception v3 (reference API: python/paddle/vision/models/inceptionv3.py;
architecture from Szegedy et al. 2015 — factorized convolutions, 299 input)."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from ._blocks import ConvBNReLU as _ConvBN

__all__ = ["InceptionV3", "inception_v3"]


class _IncA(nn.Layer):
    """1x1 + 5x5 + double-3x3 + pool-proj (35x35 grid)."""

    def __init__(self, in_ch, pool_ch):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(in_ch, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(in_ch, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_ch, pool_ch, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _IncB(nn.Layer):
    """grid reduction 35 -> 17."""

    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _ConvBN(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_ConvBN(in_ch, 64, 1),
                                 _ConvBN(64, 96, 3, padding=1),
                                 _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):
    """factorized 7x7 branches (17x17 grid)."""

    def __init__(self, in_ch, mid):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(in_ch, mid, 1),
            _ConvBN(mid, mid, (1, 7), padding=(0, 3)),
            _ConvBN(mid, 192, (7, 1), padding=(3, 0)))
        self.b7d = nn.Sequential(
            _ConvBN(in_ch, mid, 1),
            _ConvBN(mid, mid, (7, 1), padding=(3, 0)),
            _ConvBN(mid, mid, (1, 7), padding=(0, 3)),
            _ConvBN(mid, mid, (7, 1), padding=(3, 0)),
            _ConvBN(mid, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_ch, 192, 1))

    def forward(self, x):
        return paddle.concat(
            [self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _IncD(nn.Layer):
    """grid reduction 17 -> 8."""

    def __init__(self, in_ch):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(in_ch, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _ConvBN(in_ch, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(nn.Layer):
    """expanded-filter-bank block (8x8 grid)."""

    def __init__(self, in_ch):
        super().__init__()
        self.b1 = _ConvBN(in_ch, 320, 1)
        self.b3_stem = _ConvBN(in_ch, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_stem = nn.Sequential(_ConvBN(in_ch, 448, 1),
                                      _ConvBN(448, 384, 3, padding=1))
        self.b3d_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _ConvBN(in_ch, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.b3d_stem(x)
        return paddle.concat(
            [self.b1(x),
             paddle.concat([self.b3_a(s), self.b3_b(s)], axis=1),
             paddle.concat([self.b3d_a(d), self.b3d_b(d)], axis=1),
             self.bp(x)], axis=1)


class InceptionV3(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048),
        )
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    assert not pretrained, "pretrained weights are not bundled"
    return InceptionV3(**kwargs)
