"""Quantization (reference: python/paddle/quantization/ — config.py
QuantConfig, ptq.py PTQ, qat.py QAT, observers/abs_max.py, and the
quantized layers in nn/quant/; kernel analogs
paddle/phi/kernels/gpu/quantize_linear_kernel.cu).

TPU formulation: weight-only int8 is the quantization that pays on TPU
(int8 MXU runs at 2x bf16 peak; activations stay bf16/f32 and XLA fuses the
dequant scale into the matmul). PTQ calibrates per-channel abs-max scales
by running observer-wrapped forwards, then convert() swaps Linear layers
for QuantizedLinear holding int8 weights + scales. QAT wraps weights in a
straight-through fake-quant so training sees quantization error while
gradients flow unquantized."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "QuantConfig",
    "AbsMaxObserver",
    "PTQ",
    "QAT",
    "QuantizedLinear",
    "quantize_weight",
    "fake_quant",
    "ptq_convert_for_serving",
]


def quantize_weight(w, bits=8, axis=0):
    """Per-channel symmetric abs-max quantization (reference
    observers/abs_max.py). Returns (int8_values, scale)."""
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    qmax = 2 ** (bits - 1) - 1
    reduce_axes = tuple(i for i in range(wv.ndim) if i != axis)
    scale = jnp.max(jnp.abs(wv), axis=reduce_axes, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(wv / scale), -qmax - 1, qmax).astype(jnp.int8)
    return Tensor(q), Tensor(scale.astype(jnp.float32))


def fake_quant(x, scale=None, bits=8):
    """Straight-through quant-dequant (reference qat.py FakeQuant): forward
    sees the rounded value, backward is identity."""
    t = x if isinstance(x, Tensor) else to_tensor(x)
    qmax = 2 ** (bits - 1) - 1

    def fn(v):
        s = (jnp.max(jnp.abs(v)) / qmax) if scale is None else scale
        s = jnp.where(s == 0, 1.0, s)
        q = jnp.clip(jnp.round(v / s), -qmax - 1, qmax) * s
        # straight-through estimator: identity gradient
        return v + jax.lax.stop_gradient(q - v)

    return run_op("fake_quant", fn, [t])


class AbsMaxObserver:
    """reference observers/abs_max.py AbsmaxObserver."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        self._absmax = max(self._absmax, float(jnp.max(jnp.abs(v))))

    def scale(self):
        qmax = 2 ** (self.quant_bits - 1) - 1
        return (self._absmax / qmax) if self._absmax else 1.0


class QuantConfig:
    """reference config.py QuantConfig — which layer types quantize and
    with what observer."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight or AbsMaxObserver
        self._types = [nn.Linear]

    def add_type_config(self, layer_types, activation=None, weight=None):
        types = layer_types if isinstance(layer_types, (list, tuple)) else [layer_types]
        self._types.extend(t for t in types if t not in self._types)
        if weight is not None:
            self.weight = weight
        if activation is not None:
            self.activation = activation
        return self


class QuantizedLinear(nn.Layer):
    """int8-weight Linear (reference nn/quant/ QuantedLinear): stores the
    quantized weight + per-output-channel scale; the matmul dequantizes via
    the fused scale multiply XLA folds into the dot."""

    def __init__(self, linear: nn.Linear, bits=8):
        super().__init__()
        qw, scale = quantize_weight(linear.weight, bits=bits, axis=1)
        self.register_buffer("weight_quant", qw)
        self.register_buffer("weight_scale", scale)
        self.bias = linear.bias
        self.bits = bits

    def forward(self, x):
        t = x if isinstance(x, Tensor) else to_tensor(x)
        b = self.bias

        def fn(v, qw, sc, *rest):
            out = jnp.matmul(v, qw.astype(v.dtype) * sc.astype(v.dtype))
            if rest:
                out = out + rest[0]
            return out

        ins = [t, self.weight_quant, self.weight_scale]
        if b is not None:
            ins.append(b)
        return run_op("quantized_linear", fn, ins)


def _swap_sublayer(root, name, new_layer):
    """Replace the sublayer at dotted path `name` under `root` — the one
    convert-pass swap shared by PTQ.convert and ptq_convert_for_serving."""
    parts = name.split(".")
    parent = root
    for p in parts[:-1]:
        parent = getattr(parent, p)
    setattr(parent, parts[-1], new_layer)


class PTQ:
    """Post-training quantization driver (reference ptq.py PTQ):
    quantize() hooks an activation observer onto each target layer's
    forward, calibration runs feed them, convert() swaps in QuantizedLinear
    (int8 weights from weight statistics; the calibrated activation scale
    rides along on the layer for int8-activation deployment)."""

    def __init__(self, q_config: QuantConfig | None = None):
        self.config = q_config or QuantConfig()
        self._observed: list[tuple] = []

    def quantize(self, model, inplace=False):
        self._observed = []
        for name, sub in list(model.named_sublayers()):
            if any(isinstance(sub, t) for t in self.config._types) and \
                    not getattr(sub, "_ptq_observed", False):
                obs = (self.config.activation or AbsMaxObserver)()
                orig = sub.forward

                def make_fwd(orig, obs):
                    def fwd(x):
                        obs.observe(x)
                        return orig(x)
                    return fwd

                sub.forward = make_fwd(orig, obs)
                sub._ptq_observed = True
                sub._ptq_orig_forward = orig
                self._observed.append((model, name, sub, obs))
        return model

    def activation_scales(self):
        return {name: obs.scale() for _, name, _, obs in self._observed}

    def convert(self, model, inplace=False, bits=8):
        """Swap each observed Linear for its QuantizedLinear carrying the
        calibrated activation scale. Must be the model that quantize()
        instrumented — converting a different object would silently mutate
        the recorded one."""
        if self._observed and self._observed[0][0] is not model:
            raise ValueError(
                "convert() must receive the same model instance that "
                "quantize() instrumented")
        for owner, name, sub, obs in self._observed:
            sub.forward = sub._ptq_orig_forward  # unhook the observer
            ql = QuantizedLinear(sub, bits=bits)
            ql.activation_scale = obs.scale()
            _swap_sublayer(owner, name, ql)
        return model


def ptq_convert_for_serving(model, bits=8):
    """Weight-only int8 serving convert (the `PADDLE_TPU_SERVE_W8` pass):
    swap every Linear-family projection under `model` — `nn.Linear` plus the
    TP-sharded `ColumnParallelLinear`/`RowParallelLinear` the GPT/LLaMA
    decoder stacks are built from — for a `QuantizedLinear` holding int8
    weights + per-output-channel f32 scales. Embedding matrices and the LM
    head stay full precision — the tied head shares the embedding matmul,
    and an untied `lm_head` is skipped by name, so the contract holds for
    both configs.

    In place and idempotent: already-converted layers are skipped, so
    calling it twice (or constructing two engines over the same model with
    the toggle on) never double-quantizes. Weight-only is the quantization
    that pays on TPU — activations stay in the model's compute dtype and
    XLA folds the dequant scale into the matmul — and serving engines run
    single-program, so the TP sharding constraints the parallel Linears
    carry are inert there. Returns the number of layers converted."""
    from ..distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
    )

    types = (nn.Linear, ColumnParallelLinear, RowParallelLinear)
    n = 0
    for name, sub in list(model.named_sublayers()):
        if isinstance(sub, QuantizedLinear) or not isinstance(sub, types):
            continue
        # the output head is the projection most sensitive to weight
        # rounding; a tied head rides the f32 embedding matmul and never
        # reaches here, so skip the untied `lm_head` too to keep the
        # "heads stay full precision" contract config-independent
        if name.split(".")[-1] == "lm_head":
            continue
        _swap_sublayer(model, name, QuantizedLinear(sub, bits=bits))
        n += 1
    return n


class QAT:
    """Quantization-aware training (reference qat.py QAT): wraps target
    layers' forward with straight-through fake-quant on the weight."""

    def __init__(self, q_config: QuantConfig | None = None):
        self.config = q_config or QuantConfig()

    def quantize(self, model, inplace=False):
        for _name, sub in model.named_sublayers():
            if any(isinstance(sub, t) for t in self.config._types) and \
                    not getattr(sub, "_qat_wrapped", False):
                orig = sub.forward
                weight = sub.weight

                def make_fwd(orig, weight):
                    def fwd(x):
                        saved = weight._value
                        weight._value = fake_quant(Tensor(saved))._value
                        try:
                            return orig(x)
                        finally:
                            weight._value = saved
                    return fwd

                sub.forward = make_fwd(orig, weight)
                sub._qat_wrapped = True
        return model
