"""OpTest harness: systematic fwd-vs-NumPy + VJP-vs-finite-difference checks
across dtypes, eager and jitted.

Reference analog: test/legacy_test/op_test.py:418 (check_output /
check_grad) — the reference runs every op kernel against a NumPy model and
finite-difference gradients across fp32/fp64/fp16/bf16. Here one generic
harness covers the registry in tests/test_optest_sweep.py.

Checks per OpSpec:
- forward vs a NumPy reference, f32 eager + f32 under jax.jit + bf16 eager
  (bf16 compared at bf16-resolution tolerance)
- VJP vs central finite differences in f32
- bf16 VJP vs the f32 VJP (bf16 grads are computed, finite, and close)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor

__all__ = ["InSpec", "OpSpec", "check_forward", "check_grad",
           "check_forward_jit", "run_all_checks"]


@dataclasses.dataclass
class InSpec:
    shape: tuple = (3, 4)
    dtype: str = "float"  # "float" | "int" | "bool"
    low: float = -2.0
    high: float = 2.0
    # keep |x| away from non-differentiable / unstable points (|x|>eps)
    avoid_zero: bool = False


@dataclasses.dataclass
class OpSpec:
    name: str
    fn: Callable  # (*jnp arrays, **kwargs) -> jnp array (first output used)
    ref: Callable  # (*np arrays, **kwargs) -> np array
    inputs: Sequence[InSpec] = (InSpec(),)
    kwargs: dict = dataclasses.field(default_factory=dict)
    check_grad: bool = True
    check_jit: bool = True  # False for value-dependent-shape (eager-only) ops
    check_bf16: bool = True  # False where no bf16 kernel exists (LAPACK ops)
    grad_args: Sequence[int] | None = None  # default: all float inputs
    rtol: float = 2e-5
    atol: float = 2e-5
    bf16_rtol: float = 4e-2
    bf16_atol: float = 4e-2
    fd_eps: float = 1e-3
    fd_rtol: float = 8e-2
    fd_atol: float = 8e-2


def make_inputs(spec: OpSpec, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for ins in spec.inputs:
        if ins.dtype == "int":
            out.append(rng.integers(int(ins.low), int(ins.high),
                                    ins.shape).astype(np.int32))
        elif ins.dtype == "bool":
            out.append(rng.random(ins.shape) > 0.5)
        else:
            v = rng.uniform(ins.low, ins.high, ins.shape)
            if ins.avoid_zero:
                v = np.where(np.abs(v) < 0.3, np.sign(v) * 0.3 + (v == 0) * 0.3, v)
            out.append(v.astype(dtype))
    return out


def _first(out):
    if isinstance(out, (tuple, list)):
        return out[0]
    return out


def _apply(spec, vals):
    out = spec.fn(*[jnp.asarray(v) for v in vals], **spec.kwargs)
    if isinstance(out, Tensor):
        out = out._value
    elif isinstance(out, (tuple, list)):
        out = _first([o._value if isinstance(o, Tensor) else o for o in out])
    return out


def check_forward(spec: OpSpec, dtype=np.float32):
    """Eager forward vs the NumPy reference at `dtype`."""
    vals = make_inputs(spec, np.float32)
    ref = _first(spec.ref(*[np.asarray(v) for v in vals], **spec.kwargs))
    if dtype == np.float32:
        got = _apply(spec, vals)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), np.asarray(ref, np.float64),
            rtol=spec.rtol, atol=spec.atol,
            err_msg=f"{spec.name}: f32 eager forward != numpy ref")
    else:  # bf16: inputs cast to bf16, compared at bf16 resolution
        bvals = [jnp.asarray(v).astype(jnp.bfloat16)
                 if np.issubdtype(np.asarray(v).dtype, np.floating) else v
                 for v in vals]
        got = _apply(spec, bvals)
        np.testing.assert_allclose(
            np.asarray(got, np.float64), np.asarray(ref, np.float64),
            rtol=spec.bf16_rtol, atol=spec.bf16_atol,
            err_msg=f"{spec.name}: bf16 eager forward != numpy ref")


def check_forward_jit(spec: OpSpec):
    """The same op under jax.jit must match its eager output exactly-ish."""
    vals = make_inputs(spec, np.float32)
    eager = _apply(spec, vals)

    jitted = jax.jit(lambda *v: _apply(spec, v))
    got = jitted(*[jnp.asarray(v) for v in vals])
    np.testing.assert_allclose(
        np.asarray(got, np.float64), np.asarray(eager, np.float64),
        rtol=1e-6, atol=1e-6,
        err_msg=f"{spec.name}: jit forward != eager forward")


def _grad_args(spec, vals):
    if spec.grad_args is not None:
        return list(spec.grad_args)
    return [i for i, v in enumerate(vals)
            if np.issubdtype(np.asarray(v).dtype, np.floating)]


def check_grad(spec: OpSpec):
    """f32 VJP vs central finite differences, and bf16 VJP vs f32 VJP."""
    vals = make_inputs(spec, np.float32)
    gargs = _grad_args(spec, vals)
    if not gargs:
        return
    # fixed cotangent so the scalar loss probes the full jacobian row-space
    out0 = np.asarray(_apply(spec, vals), np.float64)
    ct = np.cos(np.arange(out0.size, dtype=np.float64)).reshape(out0.shape)

    def loss_np(*vs):
        return float((np.asarray(_apply(spec, vs), np.float64) * ct).sum())

    def loss_jax(*gvs):
        full = list(vals)
        for i, g in zip(gargs, gvs):
            full[i] = g
        out = _apply(spec, full)
        return (out.astype(jnp.float32) * jnp.asarray(ct, jnp.float32)).sum()

    grads = jax.grad(loss_jax, argnums=tuple(range(len(gargs))))(
        *[jnp.asarray(vals[i]) for i in gargs])
    for gi, i in enumerate(gargs):
        g = np.asarray(grads[gi], np.float64)
        v = vals[i]
        fd = np.zeros_like(np.asarray(v, np.float64))
        flat = fd.reshape(-1)
        vflat = v.reshape(-1)
        for j in range(vflat.size):
            orig = vflat[j]
            vflat[j] = orig + spec.fd_eps
            up = loss_np(*vals)
            vflat[j] = orig - spec.fd_eps
            dn = loss_np(*vals)
            vflat[j] = orig
            flat[j] = (up - dn) / (2 * spec.fd_eps)
        np.testing.assert_allclose(
            g, fd, rtol=spec.fd_rtol, atol=spec.fd_atol,
            err_msg=f"{spec.name}: analytic grad (arg {i}) != finite diff")

    # bf16 grads: computed, finite, and near the f32 grads
    bvals = [jnp.asarray(v).astype(jnp.bfloat16)
             if np.issubdtype(np.asarray(v).dtype, np.floating) else jnp.asarray(v)
             for v in vals]

    def loss_bf16(*gvs):
        full = list(bvals)
        for i, g in zip(gargs, gvs):
            full[i] = g
        out = _apply(spec, full)
        return (out.astype(jnp.float32) * jnp.asarray(ct, jnp.float32)).sum()

    bgrads = jax.grad(loss_bf16, argnums=tuple(range(len(gargs))))(
        *[bvals[i] for i in gargs])
    for gi, i in enumerate(gargs):
        bg = np.asarray(bgrads[gi].astype(jnp.float32), np.float64)
        fg = np.asarray(grads[gi], np.float64)
        assert np.isfinite(bg).all(), f"{spec.name}: non-finite bf16 grad"
        scale = max(np.abs(fg).max(), 1.0)
        np.testing.assert_allclose(
            bg / scale, fg / scale, rtol=spec.bf16_rtol, atol=spec.bf16_atol,
            err_msg=f"{spec.name}: bf16 grad drifted from f32 grad")


def check_forward_static(spec: OpSpec):
    """The op built inside a Program and replayed by Executor.run over
    feeds must match its eager output — the reference op tests' dual
    dygraph+static path (test/legacy_test/op_test.py static branch)."""
    from .. import static

    vals = make_inputs(spec, np.float32)
    eager = np.asarray(_apply(spec, vals), np.float64)
    prog = static.Program()
    with static.program_guard(prog):
        phs = []
        for i, v in enumerate(vals):
            ph = static.data(f"optest_in{i}", list(np.asarray(v).shape),
                             str(np.asarray(v).dtype))
            phs.append(ph)
        out = spec.fn(*phs, **spec.kwargs)
        if isinstance(out, (tuple, list)):
            out = out[0]
    if not prog._ops or any(out is ph for ph in phs):
        return  # identity op (e.g. atleast_1d on a >=1d input): nothing
        # recorded, the output IS the placeholder — no static path to test
    exe = static.Executor()
    (got,) = exe.run(prog,
                     feed={f"optest_in{i}": np.asarray(v)
                           for i, v in enumerate(vals)},
                     fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got, np.float64), eager,
                               rtol=spec.rtol, atol=spec.atol,
                               err_msg=f"{spec.name}: static path diverges")


def run_all_checks(spec: OpSpec):
    check_forward(spec, np.float32)
    if spec.check_bf16:
        check_forward(spec, "bfloat16")
    if spec.check_jit:
        check_forward_jit(spec)
        check_forward_static(spec)
    if spec.check_grad:
        check_grad(spec)
