"""Utility namespace (reference: python/paddle/utils/)."""

from . import cpp_extension

__all__ = ["cpp_extension"]
