"""Custom C++ operator extension (reference:
python/paddle/utils/cpp_extension/cpp_extension.py `load` + the runtime
registration in paddle/fluid/framework/custom_operator.cc).

TPU formulation: a custom op is an XLA custom call. C++ sources written
against the XLA FFI (headers shipped with jaxlib, jax.ffi.include_dir())
are compiled to a shared library at load() time, each exported handler is
registered with jax.ffi.register_ffi_target, and `custom_op` wraps the call
into the eager dispatcher (run_op) with an optional user backward wired as
jax.custom_vjp — the analog of PD_BUILD_OP + PD_BUILD_GRAD_OP. Host (CPU)
custom calls cover the reference's CPU custom-op story; device-side custom
kernels are Pallas (ops/pallas/), which needs no FFI.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["load", "custom_op", "CppExtension", "get_build_directory"]

_lock = threading.Lock()
_loaded: dict = {}


def get_build_directory():
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def _ffi_include():
    import jax

    return jax.ffi.include_dir()


def load(name, sources, extra_cxx_flags=None, build_directory=None,
         verbose=False):
    """Compile + register the handlers of a custom-op library (reference
    cpp_extension.load). `sources`: list of .cc paths. Every symbol you
    later wrap with `custom_op(..., target=...)` must be an
    XLA_FFI_DEFINE_HANDLER_SYMBOL in the sources.

    Returns the ctypes library; handlers register lazily in `custom_op`.
    """
    with _lock:
        if name in _loaded:
            return _loaded[name]
        build_dir = build_directory or get_build_directory()
        so_path = os.path.join(build_dir, f"{name}.so")
        srcs = [os.path.abspath(s) for s in sources]
        newest = max(os.path.getmtime(s) for s in srcs)
        if not os.path.exists(so_path) or os.path.getmtime(so_path) < newest:
            cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
                   f"-I{_ffi_include()}", *srcs, "-o", so_path]
            cmd += list(extra_cxx_flags or [])
            if verbose:
                print("[cpp_extension]", " ".join(cmd))
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=300)
            if r.returncode != 0:
                raise RuntimeError(
                    f"custom op build failed:\n{r.stderr[-2000:]}")
        lib = ctypes.CDLL(so_path)
        _loaded[name] = lib
        return lib


_registered: set = set()


def _register(lib, symbol, target_name, platform):
    import jax

    key = (target_name, platform)
    if key in _registered:
        return
    handler = getattr(lib, symbol)
    jax.ffi.register_ffi_target(
        target_name, jax.ffi.pycapsule(handler), platform=platform)
    _registered.add(key)


def custom_op(lib, symbol, *, name=None, platform="cpu", backward=None):
    """Wrap a registered FFI handler as an eager op (reference: the python
    API objects custom_operator.cc synthesizes per op, plus
    PD_BUILD_GRAD_OP when `backward` is given).

    Returns fn(*tensors, out_shape=None, out_dtype=None, **attrs) -> Tensor.
    `backward(residual_tensors, grad) -> tuple_of_input_grads` may itself
    call other custom ops.
    """
    import jax
    import jax.numpy as jnp

    from ..framework.core import Tensor, run_op, to_tensor

    target = name or symbol.lower()
    _register(lib, symbol, target, platform)

    def call_raw(values, out_aval, attrs):
        fn = jax.ffi.ffi_call(target, out_aval)
        return fn(*values, **attrs)

    # one stable callable per (out_aval, attrs) signature — a fresh
    # custom_vjp object per call would defeat the eager dispatch cache
    # (identity-keyed) and retrace every invocation
    _fwd_cache: dict = {}

    def _attr_key(attrs):
        # ndarray attrs are legal ffi_call inputs but unhashable; key them
        # by content
        parts = []
        for k in sorted(attrs):
            v = attrs[k]
            if hasattr(v, "tobytes"):
                parts.append((k, v.tobytes(), getattr(v, "shape", None),
                              str(getattr(v, "dtype", type(v)))))
            else:
                parts.append((k, v))
        return tuple(parts)

    def _get_fwd(out_aval, attrs):
        key = (out_aval.shape, str(out_aval.dtype), _attr_key(attrs))
        fwd = _fwd_cache.get(key)
        if fwd is not None:
            return fwd

        @jax.custom_vjp
        def fwd(*vs):
            return call_raw(vs, out_aval, attrs)

        def fwd_res(*vs):
            return fwd(*vs), vs

        def bwd(res, g):
            grads = backward([Tensor(v) for v in res], Tensor(g), **attrs)
            grads = grads if isinstance(grads, (list, tuple)) else [grads]
            out = []
            for v, gr in zip(res, grads):
                if gr is None:
                    import numpy as np

                    out.append(np.zeros(jnp.shape(v), jax.dtypes.float0))
                else:
                    out.append(gr._value if isinstance(gr, Tensor) else gr)
            return tuple(out)

        fwd.defvjp(fwd_res, bwd)
        _fwd_cache[key] = fwd
        return fwd

    def op(*tensors, out_shape=None, out_dtype=None, **attrs):
        ts = [t if isinstance(t, Tensor) else to_tensor(t) for t in tensors]
        shape = tuple(out_shape) if out_shape is not None else tuple(ts[0].shape)
        dtype = out_dtype or ts[0]._value.dtype
        out_aval = jax.ShapeDtypeStruct(shape, dtype)

        if backward is None:
            key = (shape, str(dtype), _attr_key(attrs))
            fn = _fwd_cache.get(key)
            if fn is None:
                def fn(*vs, _aval=out_aval, _attrs=attrs):
                    return call_raw(vs, _aval, _attrs)
                _fwd_cache[key] = fn
            return run_op(f"custom_{target}", fn, ts)

        return run_op(f"custom_{target}", _get_fwd(out_aval, attrs), ts)

    op.__name__ = target
    return op


class CppExtension:
    """setuptools-style descriptor (reference CppExtension); accepted by
    load() callers for API parity."""

    def __init__(self, sources, **kwargs):
        self.sources = list(sources)
        self.kwargs = kwargs
