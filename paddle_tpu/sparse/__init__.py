"""Sparse tensors (reference: python/paddle/sparse/ — creation.py
sparse_coo_tensor/sparse_csr_tensor, unary/binary ops, matmul.py,
nn/functional/activation.py; kernels paddle/phi/kernels/sparse/).

TPU formulation: sparse COO rides on jax.experimental.sparse.BCOO — XLA
compiles its gather/scatter formulation, which is the right trade on a
dense-matrix machine (the reference's cuSPARSE segmented kernels have no
TPU analog; scatter/gather lowering is what the hardware offers). CSR is a
real format (SparseCsrTensor keeps crows/cols/values; SpMM/SpMV run as
gather + segment-sum over the row pointer). SparseTensor wraps the BCOO
like Tensor wraps jax.Array and interoperates with dense Tensors via
to_dense()."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "sparse_coo_tensor",
    "sparse_csr_tensor",
    "SparseTensor",
    "SparseCsrTensor",
    "is_same_shape",
    "add",
    "subtract",
    "multiply",
    "matmul",
    "mv",
    "masked_matmul",
    "transpose",
    "nn",
]


class SparseTensor:
    """COO sparse tensor over BCOO (reference: the SparseCooTensor handle,
    paddle/phi/core/sparse_coo_tensor.h).

    `values_t` optionally carries the tape-connected values Tensor so
    autograd flows through sparse layer outputs (values()/to_dense() then
    participate in backward)."""

    def __init__(self, bcoo, values_t=None):
        self._bcoo = bcoo
        self._values_t = values_t

    # -- properties ---------------------------------------------------- #

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        if self._values_t is not None:
            return self._values_t
        return Tensor(self._bcoo.data)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    # -- conversions --------------------------------------------------- #

    def to_dense(self):
        if self._values_t is not None:
            idx = self._bcoo.indices
            shape = self._bcoo.shape

            def fn(vals):
                dense = jnp.zeros(shape, vals.dtype)
                return dense.at[
                    tuple(idx[:, d] for d in range(idx.shape[1]))].add(vals)

            return run_op("sparse_to_dense", fn, [self._values_t])
        return Tensor(self._bcoo.todense())

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def coalesce(self):
        return SparseTensor(self._bcoo.sum_duplicates())

    def to_sparse_csr(self):
        if len(self._bcoo.shape) != 2 or self._bcoo.n_dense:
            raise NotImplementedError("to_sparse_csr: 2-D COO only")
        return _coo_to_csr(self)

    # -- arithmetic ---------------------------------------------------- #

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _as_bcoo(x):
    if isinstance(x, SparseTensor):
        return x._bcoo
    raise TypeError(f"expected SparseTensor, got {type(x)}")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor.
    `indices`: [ndim, nnz]; `values`: [nnz, ...dense_dims]."""
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(
        np.asarray(indices))
    val = values._value if isinstance(values, Tensor) else jnp.asarray(
        np.asarray(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        val = val.astype(convert_dtype(dtype))
    idx = jnp.swapaxes(idx.astype(jnp.int32), 0, 1)  # BCOO wants [nnz, ndim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
        shape = shape + tuple(val.shape[1:])
    bcoo = jsparse.BCOO((val, idx), shape=tuple(shape))
    return SparseTensor(bcoo)


class SparseCsrTensor:
    """Real CSR layout (reference: paddle/phi/core/sparse_csr_tensor.h —
    crows [m+1], cols [nnz], values [nnz, ...]). Kept in CSR rather than
    converted: spmv/spmm run as a gather + segment-sum over the row
    pointer, which XLA lowers to the scatter-add formulation that is the
    TPU-native SpMM (no cuSPARSE analog needed), and crows round-trips
    exactly for checkpoint parity."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(
            crows._value if isinstance(crows, Tensor) else np.asarray(crows)
        ).astype(jnp.int32)
        self._cols = jnp.asarray(
            cols._value if isinstance(cols, Tensor) else np.asarray(cols)
        ).astype(jnp.int32)
        self._values = (values._value if isinstance(values, Tensor)
                        else jnp.asarray(np.asarray(values)))
        self._shape = tuple(int(s) for s in shape)
        if self._crows.shape[0] != self._shape[0] + 1:
            raise ValueError(
                f"crows must have shape [{self._shape[0] + 1}], got "
                f"{tuple(self._crows.shape)}")

    # -- properties ------------------------------------------------------ #

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def nnz(self):
        return int(self._cols.shape[0])

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._values)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    # -- row ids: entry e belongs to row searchsorted(crows, e, right)-1 -- #

    def _row_ids(self):
        return (jnp.searchsorted(
            self._crows, jnp.arange(self.nnz, dtype=jnp.int32),
            side="right") - 1).astype(jnp.int32)

    # -- conversions ----------------------------------------------------- #

    def to_dense(self):
        m, n = self._shape[0], self._shape[1]
        dense = jnp.zeros((m, n) + self._values.shape[1:],
                          self._values.dtype)
        return Tensor(dense.at[self._row_ids(), self._cols].add(self._values))

    def numpy(self):
        return np.asarray(self.to_dense()._value)

    def to_sparse_coo(self, sparse_dim=2):
        idx = jnp.stack([self._row_ids(), self._cols])
        return sparse_coo_tensor(Tensor(idx), Tensor(self._values),
                                 self._shape)

    def to_sparse_csr(self):
        return self

    # -- arithmetic ------------------------------------------------------ #

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    """reference: creation.py sparse_csr_tensor — true CSR storage."""
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        values = Tensor(jnp.asarray(
            values._value if isinstance(values, Tensor)
            else np.asarray(values)).astype(convert_dtype(dtype)))
    return SparseCsrTensor(crows, cols, values, shape)


def _coo_to_csr(st: "SparseTensor") -> SparseCsrTensor:
    """COO -> CSR (2-D): sort entries by (row, col), crows by bincount."""
    b = st._bcoo.sum_duplicates()
    rows = b.indices[:, 0].astype(jnp.int32)
    cols = b.indices[:, 1].astype(jnp.int32)
    m, n = b.shape[0], b.shape[1]
    order = jnp.lexsort((cols, rows))  # no int32 linearized-key overflow
    rows, cols, vals = rows[order], cols[order], b.data[order]
    crows = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(jnp.bincount(rows, length=m)).astype(jnp.int32)])
    return SparseCsrTensor(Tensor(crows), Tensor(cols), Tensor(vals), b.shape)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# --------------------------------------------------------------------------- #
# ops (reference python/paddle/sparse/binary.py, unary.py, matmul.py)
# --------------------------------------------------------------------------- #


def _csr_binary(x, y, fn_name):
    """CSR op via COO union, result back in CSR."""
    xc = x.to_sparse_coo() if isinstance(x, SparseCsrTensor) else x
    yc = y.to_sparse_coo() if isinstance(y, SparseCsrTensor) else y
    out = globals()[fn_name](xc, yc)
    return out.to_sparse_csr() if isinstance(out, SparseTensor) else out


def add(x, y):
    if isinstance(x, SparseCsrTensor) or isinstance(y, SparseCsrTensor):
        return _csr_binary(x, y, "add")
    if isinstance(y, SparseTensor):
        bx, by = _as_bcoo(x), _as_bcoo(y)
        out = jsparse.BCOO(
            (jnp.concatenate([bx.data, by.data]),
             jnp.concatenate([bx.indices, by.indices])),
            shape=bx.shape).sum_duplicates()
        return SparseTensor(out)
    # sparse + dense -> dense
    return Tensor(_as_bcoo(x).todense() + (
        y._value if isinstance(y, Tensor) else jnp.asarray(y)))


def subtract(x, y):
    if isinstance(x, SparseCsrTensor) or isinstance(y, SparseCsrTensor):
        return _csr_binary(x, y, "subtract")
    if isinstance(y, SparseTensor):
        by = _as_bcoo(y)
        neg = jsparse.BCOO((-by.data, by.indices), shape=by.shape)
        return add(x, SparseTensor(neg))
    return Tensor(_as_bcoo(x).todense() - (
        y._value if isinstance(y, Tensor) else jnp.asarray(y)))


def multiply(x, y):
    if isinstance(x, SparseCsrTensor):
        if isinstance(y, (int, float)):
            return SparseCsrTensor(Tensor(x._crows), Tensor(x._cols),
                                   Tensor(x._values * y), x._shape)
        return _csr_binary(x, y, "multiply")
    bx = _as_bcoo(x)
    if isinstance(y, SparseTensor):
        # elementwise on matching sparsity: multiply against y's dense form
        return SparseTensor(jsparse.BCOO(
            (bx.data * _gather_dense(_as_bcoo(y).todense(), bx), bx.indices),
            shape=bx.shape))
    if isinstance(y, (int, float)):
        return SparseTensor(jsparse.BCOO((bx.data * y, bx.indices),
                                         shape=bx.shape))
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return SparseTensor(jsparse.BCOO(
        (bx.data * _gather_dense(yv, bx), bx.indices), shape=bx.shape))


def _gather_dense(dense, bcoo):
    idx = tuple(bcoo.indices[:, d] for d in range(bcoo.indices.shape[1]))
    return dense[idx]


def matmul(x, y):
    """Sparse @ dense (reference matmul.py; phi/kernels/sparse/matmul_kernel).
    COO rides bcoo_dot_general; CSR is a gather + segment-sum over the row
    pointer (SpMM) — both lower to XLA scatter/gather dots. Dense outputs go
    through run_op so eager autograd flows to the dense operand and to the
    sparse values."""
    y_t = y if isinstance(y, Tensor) else to_tensor(y)
    if isinstance(x, SparseCsrTensor):
        rows, cols, m = x._row_ids(), x._cols, x._shape[0]

        def fn(vals, yv):
            gathered = vals[:, None] * yv[cols]  # [nnz, n_out]
            return jax.ops.segment_sum(
                gathered, rows, num_segments=m).astype(yv.dtype)

        return run_op("csr_spmm", fn, [Tensor(x._values), y_t])
    bx = _as_bcoo(x)

    def fn(vals, yv):
        return jsparse.BCOO((vals, bx.indices), shape=bx.shape) @ yv

    return run_op("coo_spmm", fn, [Tensor(bx.data), y_t])


def mv(x, vec):
    """Sparse matrix @ dense vector (reference: sparse/matmul.py mv —
    phi/kernels/sparse/mv_kernel). SpMV = per-entry gather + segment-sum."""
    vec_t = vec if isinstance(vec, Tensor) else to_tensor(vec)
    if isinstance(x, SparseCsrTensor):
        rows, cols, m = x._row_ids(), x._cols, x._shape[0]

        def fn(vals, vv):
            return jax.ops.segment_sum(
                vals * vv[cols], rows, num_segments=m).astype(vv.dtype)

        return run_op("csr_mv", fn, [Tensor(x._values), vec_t])
    bx = _as_bcoo(x)
    rows = bx.indices[:, 0].astype(jnp.int32)
    cols = bx.indices[:, 1]
    m = bx.shape[0]

    def fn(vals, vv):
        return jax.ops.segment_sum(
            vals * vv[cols], rows, num_segments=m).astype(vv.dtype)

    return run_op("coo_mv", fn, [Tensor(bx.data), vec_t])


def masked_matmul(x, y, mask):
    """dense @ dense sampled at `mask`'s sparsity (reference
    masked_matmul)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    bm = _as_bcoo(mask)
    rows = bm.indices[:, 0]
    cols = bm.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows], jnp.swapaxes(yv, 0, 1)[cols])
    return SparseTensor(jsparse.BCOO((vals, bm.indices),
                                     shape=(xv.shape[0], yv.shape[1])))


def transpose(x, perm):
    if isinstance(x, SparseCsrTensor):
        return transpose(x.to_sparse_coo(), perm).to_sparse_csr()
    bx = _as_bcoo(x)
    return SparseTensor(jsparse.bcoo_transpose(bx, permutation=tuple(perm)))


# --------------------------------------------------------------------------- #
# sparse.nn (reference python/paddle/sparse/nn/)
# --------------------------------------------------------------------------- #


class _SparseNN:
    def __getattr__(self, name):
        # layer classes (Conv3D, SubmConv3D, BatchNorm, MaxPool3D, ReLU,
        # ...) live in nn_layers.py; resolve lazily to avoid import cycles
        from . import nn_layers

        if name in nn_layers.__all__:
            return getattr(nn_layers, name)
        raise AttributeError(name)

    class functional:
        @staticmethod
        def relu(x):
            from . import nn_layers

            return nn_layers.ReLU()(x)

        @staticmethod
        def softmax(x, axis=-1):
            """Softmax over stored values per last-axis lane (reference
            sparse/nn/functional/activation.py softmax: zeros stay zero).
            Entries group by ALL leading indices, so ndim > 2 normalizes
            per (batch..., row) lane, not per dim-0 value."""
            bx = _as_bcoo(x)
            if axis not in (-1, len(bx.shape) - 1):
                raise NotImplementedError("sparse softmax: last axis only")
            lead = bx.indices[:, :-1].astype(jnp.int32)
            strides = np.cumprod([1] + list(bx.shape[:-1][::-1]))[::-1][1:]
            n_lanes = int(np.prod(bx.shape[:-1]))
            if n_lanes > np.iinfo(np.int32).max:
                raise NotImplementedError(
                    "sparse softmax: leading-dim product exceeds int32 lanes")
            keys = (lead * jnp.asarray(strides.copy(), jnp.int32)).sum(axis=1)
            mx = jnp.full(n_lanes, -jnp.inf).at[keys].max(bx.data)
            e = jnp.exp(bx.data - mx[keys])
            denom = jnp.zeros(n_lanes).at[keys].add(e)
            return SparseTensor(jsparse.BCOO(
                (e / denom[keys], bx.indices), shape=bx.shape))


nn = _SparseNN()


# dense -> sparse conversions as Tensor methods (reference:
# python/paddle/tensor/to_string.py Tensor.to_sparse_coo / method patching)
def _dense_to_sparse_coo(self, sparse_dim=None):
    """sparse_dim < ndim yields hybrid COO: [sparse_dim, nnz] indices with
    dense trailing dims in the values (the reference layout)."""
    v = self._value
    sd = v.ndim if sparse_dim is None else int(sparse_dim)
    mask = v != 0
    if sd < v.ndim:
        mask = mask.any(axis=tuple(range(sd, v.ndim)))
    idx = jnp.stack(jnp.nonzero(mask, size=int(np.sum(np.asarray(mask)))))
    vals = v[tuple(idx)]
    return sparse_coo_tensor(Tensor(idx), Tensor(vals), v.shape)


def _dense_to_sparse_csr(self):
    if self._value.ndim != 2:
        raise NotImplementedError("to_sparse_csr: 2-D tensors only")
    return _dense_to_sparse_coo(self).to_sparse_csr()


from ..framework.core import register_tensor_method  # noqa: E402

register_tensor_method("to_sparse_coo", _dense_to_sparse_coo)
register_tensor_method("to_sparse_csr", _dense_to_sparse_csr)
