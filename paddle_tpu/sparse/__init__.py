"""Sparse tensors (reference: python/paddle/sparse/ — creation.py
sparse_coo_tensor/sparse_csr_tensor, unary/binary ops, matmul.py,
nn/functional/activation.py; kernels paddle/phi/kernels/sparse/).

TPU formulation: sparse COO rides on jax.experimental.sparse.BCOO — XLA
compiles its gather/scatter formulation, which is the right trade on a
dense-matrix machine (the reference's cuSPARSE segmented kernels have no
TPU analog; scatter/gather lowering is what the hardware offers). CSR
construction converts to the same BCOO representation (crows expanded to
row indices). SparseTensor wraps the BCOO like Tensor wraps jax.Array and
interoperates with dense Tensors via to_dense()."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..framework.core import Tensor, to_tensor

__all__ = [
    "sparse_coo_tensor",
    "sparse_csr_tensor",
    "SparseTensor",
    "is_same_shape",
    "add",
    "subtract",
    "multiply",
    "matmul",
    "masked_matmul",
    "transpose",
    "nn",
]


class SparseTensor:
    """COO sparse tensor over BCOO (reference: the SparseCooTensor handle,
    paddle/phi/core/sparse_coo_tensor.h)."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # -- properties ---------------------------------------------------- #

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    # -- conversions --------------------------------------------------- #

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def coalesce(self):
        return SparseTensor(self._bcoo.sum_duplicates())

    # -- arithmetic ---------------------------------------------------- #

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __repr__(self):
        return (f"SparseTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def _as_bcoo(x):
    if isinstance(x, SparseTensor):
        return x._bcoo
    raise TypeError(f"expected SparseTensor, got {type(x)}")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor.
    `indices`: [ndim, nnz]; `values`: [nnz, ...dense_dims]."""
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(
        np.asarray(indices))
    val = values._value if isinstance(values, Tensor) else jnp.asarray(
        np.asarray(values))
    if dtype is not None:
        from ..framework.dtype import convert_dtype

        val = val.astype(convert_dtype(dtype))
    idx = jnp.swapaxes(idx.astype(jnp.int32), 0, 1)  # BCOO wants [nnz, ndim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
        shape = shape + tuple(val.shape[1:])
    bcoo = jsparse.BCOO((val, idx), shape=tuple(shape))
    return SparseTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """reference: creation.py sparse_csr_tensor — stored as COO (crows
    expanded), the TPU-friendly layout."""
    crows = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cols = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    indices = np.stack([rows, cols])
    return sparse_coo_tensor(indices, values, shape, dtype=dtype)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


# --------------------------------------------------------------------------- #
# ops (reference python/paddle/sparse/binary.py, unary.py, matmul.py)
# --------------------------------------------------------------------------- #


def add(x, y):
    if isinstance(y, SparseTensor):
        bx, by = _as_bcoo(x), _as_bcoo(y)
        out = jsparse.BCOO(
            (jnp.concatenate([bx.data, by.data]),
             jnp.concatenate([bx.indices, by.indices])),
            shape=bx.shape).sum_duplicates()
        return SparseTensor(out)
    # sparse + dense -> dense
    return Tensor(_as_bcoo(x).todense() + (
        y._value if isinstance(y, Tensor) else jnp.asarray(y)))


def subtract(x, y):
    if isinstance(y, SparseTensor):
        by = _as_bcoo(y)
        neg = jsparse.BCOO((-by.data, by.indices), shape=by.shape)
        return add(x, SparseTensor(neg))
    return Tensor(_as_bcoo(x).todense() - (
        y._value if isinstance(y, Tensor) else jnp.asarray(y)))


def multiply(x, y):
    bx = _as_bcoo(x)
    if isinstance(y, SparseTensor):
        # elementwise on matching sparsity: multiply against y's dense form
        return SparseTensor(jsparse.BCOO(
            (bx.data * _gather_dense(_as_bcoo(y).todense(), bx), bx.indices),
            shape=bx.shape))
    if isinstance(y, (int, float)):
        return SparseTensor(jsparse.BCOO((bx.data * y, bx.indices),
                                         shape=bx.shape))
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return SparseTensor(jsparse.BCOO(
        (bx.data * _gather_dense(yv, bx), bx.indices), shape=bx.shape))


def _gather_dense(dense, bcoo):
    idx = tuple(bcoo.indices[:, d] for d in range(bcoo.indices.shape[1]))
    return dense[idx]


def matmul(x, y):
    """Sparse @ dense (reference matmul.py; phi/kernels/sparse/matmul_kernel
    -> here XLA's scatter/gather dot via bcoo_dot_general)."""
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    out = _as_bcoo(x) @ yv
    return Tensor(out)


def masked_matmul(x, y, mask):
    """dense @ dense sampled at `mask`'s sparsity (reference
    masked_matmul)."""
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    bm = _as_bcoo(mask)
    rows = bm.indices[:, 0]
    cols = bm.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows], jnp.swapaxes(yv, 0, 1)[cols])
    return SparseTensor(jsparse.BCOO((vals, bm.indices),
                                     shape=(xv.shape[0], yv.shape[1])))


def transpose(x, perm):
    bx = _as_bcoo(x)
    return SparseTensor(jsparse.bcoo_transpose(bx, permutation=tuple(perm)))


# --------------------------------------------------------------------------- #
# sparse.nn (reference python/paddle/sparse/nn/)
# --------------------------------------------------------------------------- #


class _SparseReLU:
    def __call__(self, x):
        bx = _as_bcoo(x)
        return SparseTensor(jsparse.BCOO(
            (jnp.maximum(bx.data, 0), bx.indices), shape=bx.shape))


class _SparseNN:
    ReLU = _SparseReLU

    class functional:
        @staticmethod
        def relu(x):
            return _SparseReLU()(x)

        @staticmethod
        def softmax(x, axis=-1):
            """Softmax over stored values per last-axis lane (reference
            sparse/nn/functional/activation.py softmax: zeros stay zero).
            Entries group by ALL leading indices, so ndim > 2 normalizes
            per (batch..., row) lane, not per dim-0 value."""
            bx = _as_bcoo(x)
            if axis not in (-1, len(bx.shape) - 1):
                raise NotImplementedError("sparse softmax: last axis only")
            lead = bx.indices[:, :-1].astype(jnp.int32)
            strides = np.cumprod([1] + list(bx.shape[:-1][::-1]))[::-1][1:]
            n_lanes = int(np.prod(bx.shape[:-1]))
            if n_lanes > np.iinfo(np.int32).max:
                raise NotImplementedError(
                    "sparse softmax: leading-dim product exceeds int32 lanes")
            keys = (lead * jnp.asarray(strides.copy(), jnp.int32)).sum(axis=1)
            mx = jnp.full(n_lanes, -jnp.inf).at[keys].max(bx.data)
            e = jnp.exp(bx.data - mx[keys])
            denom = jnp.zeros(n_lanes).at[keys].add(e)
            return SparseTensor(jsparse.BCOO(
                (e / denom[keys], bx.indices), shape=bx.shape))


nn = _SparseNN()
