"""sparse.nn layers (reference: python/paddle/sparse/nn/ — layer/conv.py
Conv3D/SubmConv3D :471/:184, layer/norm.py BatchNorm :27, layer/pooling.py
MaxPool3D, layer/activation.py; kernels paddle/phi/kernels/sparse/gpu/
conv_kernel.cu).

TPU stance: the reference's gather-GEMM-scatter sparse convolution exists
because GPU dense conv wastes FLOPs on empty voxels; the TPU is a
dense-matrix machine whose conv path is the MXU, so sparse convs LOWER TO
DENSE convolution (XLA conv_general_dilated) while keeping the sparse COO
format at the API boundary. Submanifold convs mask the dense result back to
the input's active sites — the defining SubmConv semantic. BatchNorm and
activations operate on the [nnz, C] value rows directly (the reference's
per-active-site semantics)."""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op
from ..nn import Layer, ParamAttr
from ..nn import initializer as I
from . import SparseTensor, sparse_coo_tensor

__all__ = ["Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D", "BatchNorm",
           "MaxPool3D", "ReLU", "LeakyReLU", "ReLU6", "Softmax"]


def _dense_from_coo(st: SparseTensor):
    """Tape-aware densify: rides SparseTensor.to_dense()'s _values_t path so
    gradients flow through STACKED sparse layers, not just the last one."""
    return st.to_dense()


def _to_hybrid_coo(dense_t: Tensor, ndim_sparse):
    """dense Tensor [N, *spatial, C] -> COO over the leading dims with
    [nnz, C] values. The site gather runs through run_op so the returned
    sparse tensor's values stay on the autograd tape."""
    dense = dense_t._value
    mask = jnp.any(dense != 0, axis=-1)
    nnz = int(np.sum(np.asarray(mask)))
    idx = jnp.stack(jnp.nonzero(mask, size=nnz))
    idx_t = tuple(idx[d] for d in range(idx.shape[0]))
    vals_t = run_op("sparse_gather_sites", lambda d: d[idx_t], [dense_t])
    st = sparse_coo_tensor(Tensor(idx), Tensor(vals_t._value), dense.shape)
    st._values_t = vals_t
    return st


class _SparseConvND(Layer):
    _spatial = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        n = self._spatial
        ks = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
        self._stride = (stride,) * n if isinstance(stride, int) else tuple(stride)
        self._padding = (padding,) * n if isinstance(padding, int) else tuple(padding)
        self._dilation = (dilation,) * n if isinstance(dilation, int) else tuple(dilation)
        self._groups = groups
        self._subm = subm
        if subm and (any(s != 1 for s in self._stride)):
            raise ValueError("SubmConv requires stride 1 (sparsity-preserving)")
        # weight layout [*ks, in/groups, out] (reference sparse conv layout)
        self.weight = self.create_parameter(
            list(ks) + [in_channels // groups, out_channels],
            attr=weight_attr, default_initializer=I.XavierUniform())
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True))

    def forward(self, x: SparseTensor):
        n = self._spatial
        dn_spec = ("NDHWC", "DHWIO", "NDHWC") if n == 3 else \
            ("NHWC", "HWIO", "NHWC")
        stride, padding, dilation = self._stride, self._padding, self._dilation
        groups, subm = self._groups, self._subm
        has_bias = self.bias is not None
        dense_in = _dense_from_coo(x)  # Tensor (tape-connected)
        idx = x._bcoo.indices  # [nnz, n+1] (batch + spatial), static

        def fn(dense, w, *rest):
            out = jax.lax.conv_general_dilated(
                dense, w,
                window_strides=stride,
                padding=[(p, p) for p in padding],
                rhs_dilation=dilation,
                dimension_numbers=dn_spec,
                feature_group_count=groups,
            )
            if has_bias:
                out = out + rest[0]
            if subm:
                # submanifold: only the input's active sites stay active
                mask = jnp.zeros(out.shape[:-1], bool).at[
                    tuple(idx[:, d] for d in range(idx.shape[1]))].set(True)
                out = jnp.where(mask[..., None], out, 0.0)
            return out

        ins = [dense_in, self.weight]
        if has_bias:
            ins.append(self.bias)
        out = run_op("sparse_conv", fn, ins)
        return _to_hybrid_coo(out, n + 1)


class Conv3D(_SparseConvND):
    """reference: sparse/nn/layer/conv.py Conv3D :471 (NDHWC)."""

    _spatial = 3


class SubmConv3D(_SparseConvND):
    """reference: sparse/nn/layer/conv.py SubmConv3D :184 — output sparsity
    equals input sparsity."""

    _spatial = 3

    def __init__(self, *args, **kwargs):
        kwargs["subm"] = True
        super().__init__(*args, **kwargs)


class Conv2D(_SparseConvND):
    _spatial = 2


class SubmConv2D(_SparseConvND):
    _spatial = 2

    def __init__(self, *args, **kwargs):
        kwargs["subm"] = True
        super().__init__(*args, **kwargs)


class BatchNorm(Layer):
    """reference: sparse/nn/layer/norm.py BatchNorm — normalizes the ACTIVE
    value rows per channel (empty voxels do not contribute statistics)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean",
                             Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance",
                             Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x: SparseTensor):
        b = x._bcoo
        training = self.training
        mom, eps = self._momentum, self._epsilon

        def fn(vals, w, bias):
            v32 = vals.astype(jnp.float32)
            if training:
                mu = v32.mean(0)
                var = v32.var(0)
            else:
                mu = self._mean._value
                var = self._variance._value
            out = (v32 - mu) * jax.lax.rsqrt(var + eps) * w + bias
            return out.astype(vals.dtype), mu, var

        out_vals, mu_t, var_t = run_op(
            "sparse_batch_norm", fn, [x.values(), self.weight, self.bias],
            n_outputs=3)
        if training:
            # stats computed ONCE inside the op; running update on device
            self._mean._value = (mom * self._mean._value
                                 + (1 - mom) * mu_t._value)
            self._variance._value = (mom * self._variance._value
                                     + (1 - mom) * var_t._value)
        import jax.experimental.sparse as jsparse

        return SparseTensor(jsparse.BCOO((out_vals._value, b.indices),
                                         shape=b.shape), values_t=out_vals)


class MaxPool3D(Layer):
    """reference: sparse/nn/layer/pooling.py MaxPool3D (NDHWC)."""

    def __init__(self, kernel_size, stride=None, padding=0, name=None):
        super().__init__()
        k = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
        s = k if stride is None else (
            (stride,) * 3 if isinstance(stride, int) else tuple(stride))
        p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
        self._k, self._s, self._p = k, s, p

    def forward(self, x: SparseTensor):
        k, s, p = self._k, self._s, self._p
        dense_t = _dense_from_coo(x)

        def fn(d):
            return jax.lax.reduce_window(
                d, -jnp.inf, jax.lax.max,
                window_dimensions=(1,) + k + (1,),
                window_strides=(1,) + s + (1,),
                padding=[(0, 0)] + [(pp, pp) for pp in p] + [(0, 0)])

        pooled = run_op("sparse_max_pool3d", fn, [dense_t])
        finite = run_op("sparse_pool_mask",
                        lambda v: jnp.where(jnp.isfinite(v), v, 0.0),
                        [pooled])
        return _to_hybrid_coo(finite, 4)


class _ValueAct(Layer):
    _fn = staticmethod(lambda v: v)

    def forward(self, x):
        from . import SparseCsrTensor

        fn = self._fn
        if isinstance(x, SparseCsrTensor):
            out = run_op("sparse_act", lambda v: fn(v), [x.values()])
            return SparseCsrTensor(x.crows(), x.cols(), out, x.shape)
        import jax.experimental.sparse as jsparse

        b = x._bcoo
        out = run_op("sparse_act", lambda v: fn(v), [x.values()])
        return SparseTensor(jsparse.BCOO((out._value, b.indices),
                                         shape=b.shape), values_t=out)


class ReLU(_ValueAct):
    _fn = staticmethod(lambda v: jnp.maximum(v, 0))


class ReLU6(_ValueAct):
    _fn = staticmethod(lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(_ValueAct):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        a = float(negative_slope)
        self._fn = lambda v: jnp.where(v > 0, v, a * v)


class Softmax(Layer):
    """Defers to the existing per-lane sparse softmax."""

    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        from . import nn as _fns

        return _fns.functional.softmax(x, axis=self._axis)
