"""Per-parameter regularizers (reference: python/paddle/regularizer.py
L1Decay/L2Decay; applied by append_regularization_ops before clipping).

A ParamAttr(regularizer=...) attaches one of these to a Parameter; the
optimizer adds its gradient contribution before grad clipping, matching
the reference order. A per-param regularizer takes precedence over the
optimizer-level weight_decay for that parameter."""

from __future__ import annotations

import jax.numpy as jnp

from .framework.core import run_op

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    """grad += coeff * sign(param) (reference regularizer.py L1Decay)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __call__(self, param):
        c = self._coeff
        return run_op("l1_decay_grad",
                      lambda p: c * jnp.sign(p), [param])

    def __repr__(self):
        return f"L1Decay(coeff={self._coeff})"


class L2Decay:
    """grad += coeff * param (reference regularizer.py L2Decay)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    def __call__(self, param):
        c = self._coeff
        return run_op("l2_decay_grad", lambda p: c * p, [param])

    def __repr__(self):
        return f"L2Decay(coeff={self._coeff})"
