"""Optimizer base + the standard family (reference: python/paddle/optimizer/).

Design: every optimizer defines a PURE update rule
    init_state(param_value) -> state dict of jax arrays
    update(param, grad, state, lr, ctx) -> (new_param, new_state)
Eager `step()` walks params and applies it; the jit path
(paddle_tpu.jit.functional_optimizer) maps the same rule over a params pytree
inside one compiled program — replacing the reference's multi_tensor/fused
optimizer kernels (paddle/phi/kernels/gpu/adamw_kernel.cu etc.) with one
XLA-fused update.

Master weights: with multi_precision=True bf16/f16 params keep an f32 master
copy in the state (reference: master-weight support across optimizer kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtype_mod
from ..framework.core import Parameter, Tensor, no_grad
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "Lars",
]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._weight_decay = 0.0 if weight_decay is None else weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._states: dict[int, dict] = {}
        self._step_count = 0

    # -- lr ---------------------------------------------------------------- #

    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("set_lr cannot be used with an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # -- pure update rule (overridden per optimizer) ----------------------- #

    def init_state(self, p):
        return {}

    def update(self, p, g, state, lr, ctx):
        raise NotImplementedError

    def _decay_coeff(self):
        wd = self._weight_decay
        if hasattr(wd, "__float__"):
            return float(wd)
        return float(wd) if wd else 0.0

    # -- eager step --------------------------------------------------------- #

    def _get_state(self, param):
        key = id(param)
        st = self._states.get(key)
        if st is None:
            pv = param._value
            st = self.init_state(pv)
            if self._multi_precision and pv.dtype in (jnp.bfloat16, jnp.float16):
                st["master"] = pv.astype(jnp.float32)
            self._states[key] = st
        return st

    @no_grad()
    def _collect_params_grads(self):
        """Flatten param groups, add per-param regularizer grads (BEFORE
        clipping — reference append_regularization_ops order), clip."""
        flat = []
        for p in self._parameter_list or []:
            if isinstance(p, dict):
                flat.extend(p["params"])
            else:
                flat.append(p)
        params_grads = [(p, p.grad) for p in flat
                        if not p.stop_gradient and p.grad is not None]
        params_grads = [
            (p, g + p.regularizer(p)) if getattr(p, "regularizer", None)
            else (p, g)
            for p, g in params_grads]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        return params_grads

    def _param_wd(self, p, base_wd):
        """A per-param regularizer REPLACES the optimizer-level coeff for
        that param (reference semantics) — never both."""
        return 0.0 if getattr(p, "regularizer", None) else base_wd

    @no_grad()
    def step(self):
        if self._parameter_list is None:
            raise ValueError("optimizer constructed without parameters")
        params_grads = self._collect_params_grads()
        self._step_count += 1
        base_wd = self._decay_coeff()
        lr = self.get_lr()
        for p, g in params_grads:
            ctx = {"step": self._step_count,
                   "weight_decay": self._param_wd(p, base_wd)}
            st = self._get_state(p)
            pv = st.get("master", p._value)
            gv = g._value.astype(pv.dtype)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0) if hasattr(p, "optimize_attr") else lr
            new_p, new_st = self.update(pv, gv, {k: v for k, v in st.items() if k != "master"}, plr, ctx)
            if "master" in st:
                st["master"] = new_p
                p._value = new_p.astype(p._value.dtype)
            else:
                p._value = new_p
            for k, v in new_st.items():
                st[k] = v

    @no_grad()
    def clear_grad(self, set_to_zero=True):
        params = self._parameter_list or []
        for p in params:
            if isinstance(p, dict):
                for q in p["params"]:
                    q.clear_grad()
            else:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    # -- state dict --------------------------------------------------------- #

    def state_dict(self):
        out = {"_step_count": self._step_count}
        flat = []
        for p in self._parameter_list or []:
            if isinstance(p, dict):
                flat.extend(p["params"])
            else:
                flat.append(p)
        for i, p in enumerate(flat):
            st = self._states.get(id(p))
            if st:
                out[f"param_{i}"] = {k: Tensor(v) for k, v in st.items()}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("_step_count", 0)
        flat = []
        for p in self._parameter_list or []:
            if isinstance(p, dict):
                flat.extend(p["params"])
            else:
                flat.append(p)
        for i, p in enumerate(flat):
            key = f"param_{i}"
            if key in state:
                self._states[id(p)] = {
                    k: (v._value if isinstance(v, Tensor) else jnp.asarray(v))
                    for k, v in state[key].items()
                }
        if "LR_Scheduler" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["LR_Scheduler"])


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p, dtype=jnp.float32 if p.dtype in (jnp.bfloat16, jnp.float16) else p.dtype)}

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        v = self._momentum * state["velocity"].astype(g.dtype) + g
        if self._nesterov:
            upd = g + self._momentum * v
        else:
            upd = v
        return p - lr * upd, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, moment_dtype=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._decoupled = False  # Adam: L2 into grad; AdamW: decoupled
        # TPU extension: store m/v in a low-precision dtype (e.g. "bfloat16")
        # so a 1.3B AdamW fits one 16GB chip — halves optimizer-state HBM.
        # The update still computes in f32 (reference fused_adam MPType,
        # paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu accumulates in
        # MPDType regardless of storage dtype).
        self._moment_dtype = (getattr(jnp, moment_dtype)
                              if isinstance(moment_dtype, str) else moment_dtype)

    def init_state(self, p):
        if self._moment_dtype is not None:
            mdt = self._moment_dtype
        else:
            mdt = jnp.float32 if p.dtype in (jnp.bfloat16, jnp.float16) else p.dtype
        return {"m": jnp.zeros_like(p, dtype=mdt), "v": jnp.zeros_like(p, dtype=mdt)}

    def update(self, p, g, state, lr, ctx):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = ctx["step"]
        wd = ctx["weight_decay"]
        # compute in f32, store back in each tensor's own dtype — exact
        # no-op for the default all-f32 path
        m_dt, v_dt, p_dt = state["m"].dtype, state["v"].dtype, p.dtype
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if wd and not self._decoupled:
            g32 = g32 + wd * p32
        m = b1 * state["m"].astype(jnp.float32) + (1 - b1) * g32
        v = b2 * state["v"].astype(jnp.float32) + (1 - b2) * jnp.square(g32)
        mhat = m / (1 - b1**t)
        vhat = v / (1 - b2**t)
        upd = mhat / (jnp.sqrt(vhat) + eps)
        if wd and self._decoupled:
            upd = upd + wd * p32
        return ((p32 - lr * upd).astype(p_dt),
                {"m": m.astype(m_dt), "v": v.astype(v_dt)})


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, moment_dtype=None, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         moment_dtype=moment_dtype, name=name)
        self._decoupled = True
        self._apply_decay_param_fun = apply_decay_param_fun

    @no_grad()
    def step(self):
        # honor apply_decay_param_fun by zeroing decay per param
        if self._apply_decay_param_fun is None:
            return super().step()
        base_wd = self._decay_coeff()
        params_grads = self._collect_params_grads()
        self._step_count += 1
        lr = self.get_lr()
        for p, g in params_grads:
            wd = self._param_wd(p, base_wd) \
                if self._apply_decay_param_fun(p.name or "") else 0.0
            ctx = {"step": self._step_count, "weight_decay": wd}
            st = self._get_state(p)
            pv = st.get("master", p._value)
            gv = g._value.astype(pv.dtype)
            new_p, new_st = self.update(pv, gv, {k: v for k, v in st.items() if k != "master"}, lr, ctx)
            if "master" in st:
                st["master"] = new_p
                p._value = new_p.astype(p._value.dtype)
            else:
                p._value = new_p
            st.update(new_st)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, ctx):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = ctx["step"]
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        m = b1 * state["m"] + (1 - b1) * g
        u = jnp.maximum(b2 * state["u"], jnp.abs(g))
        return p - lr / (1 - b1**t) * m / (u + eps), {"m": m, "u": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        mom = state["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(mom) + self._epsilon), {"moment": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._epsilon, self._rho = epsilon, rho

    def init_state(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p), "avg_sq_update": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        eps, rho = self._epsilon, self._rho
        asg = rho * state["avg_sq_grad"] + (1 - rho) * jnp.square(g)
        upd = jnp.sqrt(state["avg_sq_update"] + eps) / jnp.sqrt(asg + eps) * g
        asu = rho * state["avg_sq_update"] + (1 - rho) * jnp.square(upd)
        return p - lr * upd, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, centered

    def init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p), "velocity": jnp.zeros_like(p)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p)
        return st

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        rho, eps = self._rho, self._epsilon
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(g)
        new_state = {"mean_square": ms}
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
            new_state["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + eps)
        v = self._momentum * state["velocity"] + lr * g / denom
        new_state["velocity"] = v
        return p - v, new_state


class Lamb(Optimizer):
    """reference: python/paddle/optimizer/lamb.py (and the fused
    distributed_fused_lamb kernel) — layer-wise trust ratio on AdamW."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip, multi_precision, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        f32 = jnp.float32 if p.dtype in (jnp.bfloat16, jnp.float16) else p.dtype
        return {"m": jnp.zeros_like(p, dtype=f32), "v": jnp.zeros_like(p, dtype=f32)}

    def update(self, p, g, state, lr, ctx):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = ctx["step"]
        wd = ctx["weight_decay"]
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1**t)
        vhat = v / (1 - b2**t)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * p
        w_norm = jnp.linalg.norm(p.reshape(-1))
        r_norm = jnp.linalg.norm(r.reshape(-1))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {"m": m, "v": v}


class Lars(Momentum):
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, multi_precision=False, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=lars_weight_decay, grad_clip=grad_clip,
                         multi_precision=multi_precision, name=name)
        self._lars_coeff = lars_coeff

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        w_norm = jnp.linalg.norm(p.reshape(-1))
        g_norm = jnp.linalg.norm(g.reshape(-1))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + wd * w_norm + 1e-12),
            1.0,
        )
        g = g + wd * p
        v = self._momentum * state["velocity"].astype(g.dtype) + local_lr * g
        return p - lr * v, {"velocity": v}


class NAdam(Optimizer):
    """reference: python/paddle/optimizer/nadam.py (Nesterov-momentum Adam)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p),
                "mu_prod": jnp.ones((), jnp.float32)}

    def update(self, p, g, state, lr, ctx):
        b1, b2, eps, psi = self._beta1, self._beta2, self._epsilon, self._psi
        t = jnp.asarray(ctx["step"], jnp.float32)
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * psi))
        mu_next = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * psi))
        mu_prod = state["mu_prod"] * mu_t
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * g * g
        m_hat = (mu_next * m / (1 - mu_prod * mu_next)
                 + (1 - mu_t) * g / (1 - mu_prod))
        v_hat = v / (1 - b2 ** t)
        return (p - lr * m_hat / (jnp.sqrt(v_hat) + eps),
                {"m": m, "v": v, "mu_prod": mu_prod})


class RAdam(Optimizer):
    """reference: python/paddle/optimizer/radam.py (rectified Adam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name=name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def update(self, p, g, state, lr, ctx):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        t = jnp.asarray(ctx["step"], jnp.float32)
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        m = b1 * state["m"] + (1 - b1) * g
        v = b2 * state["v"] + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2 ** t / (1 - b2 ** t)
        r_num = (rho_t - 4) * (rho_t - 2) * rho_inf
        r_den = (rho_inf - 4) * (rho_inf - 2) * rho_t
        rect = jnp.sqrt(jnp.maximum(r_num / r_den, 0.0))
        v_hat = jnp.sqrt(v / (1 - b2 ** t))
        adaptive = rect * m_hat / (v_hat + eps)
        sgd_like = m_hat
        return (p - lr * jnp.where(rho_t > 5.0, adaptive, sgd_like),
                {"m": m, "v": v})


class Rprop(Optimizer):
    """reference: python/paddle/optimizer/rprop.py (sign-based resilient
    propagation; full-batch method)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name=name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def init_state(self, p):
        return {"prev_g": jnp.zeros_like(p),
                "step_size": jnp.full_like(p, self.get_lr())}

    def update(self, p, g, state, lr, ctx):
        sign = jnp.sign(g * state["prev_g"])
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        step = jnp.clip(state["step_size"] * factor, self._lr_min, self._lr_max)
        g_eff = jnp.where(sign < 0, 0.0, g)  # backtrack: skip update
        return (p - step * jnp.sign(g_eff),
                {"prev_g": g_eff, "step_size": step})


class ASGD(Optimizer):
    """reference: python/paddle/optimizer/asgd.py (averaged SGD)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=multi_precision, name=name)
        self._n = max(int(batch_num), 1)

    def init_state(self, p):
        # under multi_precision the update runs on the f32 master weights,
        # so the grad history must be f32 too (dynamic_update_slice is
        # dtype-strict)
        dt = (jnp.float32 if self._multi_precision
              and p.dtype in (jnp.bfloat16, jnp.float16) else p.dtype)
        return {"d": jnp.zeros(p.shape, dt),
                "ys": jnp.zeros((self._n,) + p.shape, dt),
                "idx": jnp.zeros((), jnp.int32)}

    def update(self, p, g, state, lr, ctx):
        wd = ctx["weight_decay"]
        if wd:
            g = g + wd * p
        g = g.astype(state["ys"].dtype)
        i = state["idx"] % self._n
        old = jax.lax.dynamic_index_in_dim(state["ys"], i, 0, keepdims=False)
        d = state["d"] - old + g
        ys = jax.lax.dynamic_update_index_in_dim(state["ys"], g, i, 0)
        return (p - lr / self._n * d,
                {"d": d, "ys": ys, "idx": state["idx"] + 1})
