"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""

from . import lr
from .optimizer import (
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    Lars,
    Momentum,
    Optimizer,
    RMSProp,
)

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "Lars", "lr",
]
