"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""

from . import lr
from .optimizer import (
    SGD,
    Adadelta,
    Adagrad,
    Adam,
    Adamax,
    AdamW,
    Lamb,
    NAdam,
    RAdam,
    Rprop,
    ASGD,
    Lars,
    Momentum,
    Optimizer,
    RMSProp,
)
from .lbfgs import LBFGS

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
    "Adadelta", "RMSProp", "Lamb", "Lars", "NAdam", "RAdam", "Rprop", "ASGD",
    "LBFGS", "lr",
]
