"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py —
closure-based step(), two-loop recursion, strong-Wolfe line search).

TPU note: L-BFGS is a full-batch method driven by host-side control flow
(line-search iterations re-evaluate the closure), so the implementation is
eager by design — each closure call is itself a compiled forward/backward."""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor, no_grad
from .optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    """reference lbfgs.py LBFGS. Usage:

        def closure():
            opt.clear_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            return loss

        loss = opt.step(closure)
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if grad_clip is not None:
            raise ValueError(
                "LBFGS does not support grad_clip: clipping the line-search "
                "gradients breaks the Wolfe conditions (the reference LBFGS "
                "has no grad_clip either)")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name=name)
        self.max_iter = max_iter
        self.max_eval = max_eval or max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self.line_search_fn = line_search_fn
        self._s: list = []   # param deltas
        self._y: list = []   # grad deltas

    # curvature history must survive checkpointing, or a resumed LBFGS
    # silently degrades to steepest descent
    def state_dict(self):
        out = super().state_dict()
        out["lbfgs_s"] = [Tensor(s) for s in self._s]
        out["lbfgs_y"] = [Tensor(y) for y in self._y]
        return out

    def set_state_dict(self, state):
        # non-destructive: popping would silently strip the curvature
        # history out of the caller's checkpoint dict
        self._s = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                   for t in state.get("lbfgs_s", [])]
        self._y = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                   for t in state.get("lbfgs_y", [])]
        super().set_state_dict(
            {k: v for k, v in state.items()
             if k not in ("lbfgs_s", "lbfgs_y")})

    # ------------------------------------------------------------------ #

    def _params(self):
        flat = []
        for p in self._parameter_list or []:
            if isinstance(p, dict):
                flat.extend(p["params"])
            else:
                flat.append(p)
        return [p for p in flat if not p.stop_gradient]

    def _gather_flat_grad(self, params):
        gs = []
        wd = self._decay_coeff()
        for p in params:
            g = p.grad._value if p.grad is not None else jnp.zeros_like(p._value)
            g = g.astype(jnp.float32)
            if wd:  # L2 decay folds into the objective's gradient
                g = g + wd * p._value.astype(jnp.float32)
            gs.append(jnp.ravel(g))
        return jnp.concatenate(gs)

    def _gather_flat_params(self, params):
        return jnp.concatenate(
            [jnp.ravel(p._value.astype(jnp.float32)) for p in params])

    def _set_flat_params(self, params, flat):
        off = 0
        for p in params:
            n = int(np.prod(p.shape)) if p.shape else 1
            p._value = flat[off:off + n].reshape(p._value.shape).astype(
                p._value.dtype)
            off += n

    def _direction(self, flat_grad):
        """Two-loop recursion over the (s, y) history."""
        q = -flat_grad
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / float(jnp.dot(y, s))
            a = rho * float(jnp.dot(s, q))
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = float(jnp.dot(s, y) / jnp.dot(y, y))
            q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.dot(y, q))
            q = q + (a - b) * s
        return q

    def _decay_term(self, params):
        wd = self._decay_coeff()
        if not wd:
            return 0.0
        # the line search must test f and g of the SAME objective: the L2
        # term folded into _gather_flat_grad needs its 0.5*wd*||p||^2 value
        # counterpart here
        return 0.5 * wd * float(sum(
            jnp.sum(jnp.square(p._value.astype(jnp.float32)))
            for p in params))

    @no_grad()
    def step(self, closure):
        """One L-BFGS outer step; `closure` re-evaluates loss + grads."""
        params = self._params()
        with _grad_enabled():
            loss = closure()
        loss_val = float(loss.numpy()) + self._decay_term(params)
        flat_grad = self._gather_flat_grad(params)
        n_evals = 1
        lr = self.get_lr()

        for _it in range(self.max_iter):
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tol_grad:
                break
            d = self._direction(flat_grad)
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -1e-16:  # not a descent direction: reset history
                self._s.clear()
                self._y.clear()
                d = -flat_grad
                gtd = float(jnp.dot(flat_grad, d))
            t = lr if (self._s or _it > 0) else min(
                1.0, 1.0 / max(float(jnp.sum(jnp.abs(flat_grad))), 1e-12)) * lr

            x0 = self._gather_flat_params(params)

            def eval_at(step_size):
                self._set_flat_params(params, x0 + step_size * d)
                with _grad_enabled():
                    ls = closure()
                return (float(ls.numpy()) + self._decay_term(params),
                        self._gather_flat_grad(params))

            if self.line_search_fn == "strong_wolfe":
                t, new_loss, new_grad, evals = _strong_wolfe(
                    eval_at, t, loss_val, flat_grad, d, gtd)
                n_evals += evals
            else:
                new_loss, new_grad = eval_at(t)
                n_evals += 1

            s = t * d
            y = new_grad - flat_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)

            if abs(new_loss - loss_val) < self.tol_change:
                loss_val, flat_grad = new_loss, new_grad
                break
            loss_val, flat_grad = new_loss, new_grad
            if n_evals >= self.max_eval:
                break

        self._step_count += 1
        return Tensor(jnp.asarray(loss_val, jnp.float32))


def _strong_wolfe(eval_at, t, f0, g0, d, gtd0, c1=1e-4, c2=0.9, max_ls=10):
    """Bracketing strong-Wolfe line search (reference lbfgs.py
    _strong_wolfe)."""
    f_prev, t_prev = f0, 0.0
    evals = 0
    f_new, g_new = eval_at(t)
    evals += 1
    for i in range(max_ls):
        gtd_new = float(jnp.dot(g_new, d))
        if f_new > f0 + c1 * t * gtd0 or (i > 0 and f_new >= f_prev):
            return _zoom(eval_at, t_prev, t, f_prev, f_new, f0, gtd0, d,
                         c1, c2, evals)
        if abs(gtd_new) <= -c2 * gtd0:
            return t, f_new, g_new, evals
        if gtd_new >= 0:
            return _zoom(eval_at, t, t_prev, f_new, f_prev, f0, gtd0, d,
                         c1, c2, evals)
        t_prev, f_prev = t, f_new
        t = t * 2.0
        f_new, g_new = eval_at(t)
        evals += 1
    return t, f_new, g_new, evals


def _zoom(eval_at, lo, hi, f_lo, f_hi, f0, gtd0, d, c1, c2, evals,
          max_zoom=10):
    t = lo
    f_new, g_new = f_lo, None
    for _ in range(max_zoom):
        t = 0.5 * (lo + hi)
        f_new, g_new = eval_at(t)
        evals += 1
        if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
            hi, f_hi = t, f_new
        else:
            gtd_new = float(jnp.dot(g_new, d))
            if abs(gtd_new) <= -c2 * gtd0:
                break
            if gtd_new * (hi - lo) >= 0:
                hi, f_hi = lo, f_lo
            lo, f_lo = t, f_new
    if g_new is None:
        f_new, g_new = eval_at(t)
        evals += 1
    return t, f_new, g_new, evals


class _grad_enabled:
    """Re-enable autograd inside step()'s no_grad scope for closure calls."""

    def __enter__(self):
        from ..framework.core import is_grad_enabled, set_grad_enabled

        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        from ..framework.core import set_grad_enabled

        set_grad_enabled(self._prev)
        return False
