"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = [
    "BatchNorm",
    "BatchNorm1D",
    "BatchNorm2D",
    "BatchNorm3D",
    "SyncBatchNorm",
    "LayerNorm",
    "RMSNorm",
    "GroupNorm",
    "InstanceNorm1D",
    "InstanceNorm2D",
    "InstanceNorm3D",
    "LocalResponseNorm",
    "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats,
        )

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}, epsilon={self._epsilon}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else data_format,
                         use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (reference: python/paddle/nn/layer/norm.py SyncBatchNorm).
    Under pjit/shard_map the batch axis is a mesh axis and XLA's batch-norm
    reductions become cross-replica psums automatically when inside shard_map;
    in single-process eager it equals BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight._value = layer.weight._value
            if layer.bias is not None:
                new.bias._value = layer.bias._value
            new._mean._value = layer._mean._value
            new._variance._value = layer._variance._value
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """reference: paddle.incubate.nn.FusedRMSNorm / rms_norm
    (python/paddle/incubate/nn/functional/fused_rms_norm.py)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)
        if weight_attr is False:
            self.weight = None
        if bias_attr is False:
            self.bias = None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=I.Constant(1.0)
            )
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Spectral norm via power iteration (reference: python/paddle/nn/layer/norm.py
    SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter([h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v = self.create_parameter([w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...framework.core import run_op
        import jax

        dim, eps, iters = self._dim, self._epsilon, self._power_iters

        def fn(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma

        return run_op("spectral_norm", fn, [weight, self.weight_u, self.weight_v])
