"""Layer: the module base class.

Reference: paddle.nn.Layer (python/paddle/nn/layer/layers.py:353) — parameter /
buffer / sublayer registries, hooks, state_dict with structured names,
train/eval mode, dtype casting. Redesigned for JAX: parameters are
Tensor handles over jax.Arrays, and `functional_state()` / `load_functional_state()`
expose the layer tree as a pytree so the whole model drops into jax.jit /
jax.grad / pjit without touching user code (paddle_tpu.jit builds on this).
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import jax.numpy as jnp
import numpy as np

from ...framework import dtype as dtype_mod
from ...framework.core import Parameter, Tensor
from .. import initializer as I

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/base/param_attr.py)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot interpret {attr!r} as ParamAttr")


_layer_counter = collections.defaultdict(int)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        cls = self.__class__.__name__.lower()
        _layer_counter[cls] += 1
        self._full_name = f"{name_scope or cls}_{_layer_counter[cls] - 1}"
        self._dtype = dtype_mod.convert_dtype(dtype)
        self.training = True
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names: set[str] = set()
        self._sub_layers: dict[str, Layer] = collections.OrderedDict()
        self._forward_pre_hooks: dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0

    # ------------------------------------------------------------------ #
    # registration
    # ------------------------------------------------------------------ #

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                else:
                    buffers.pop(name, None)
            object.__setattr__(self, name, value)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        object.__setattr__(self, str(name), sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        """reference: Layer.create_parameter (nn/layer/layers.py) — default init
        Xavier-uniform for weights, zeros for biases."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        d = dtype_mod.convert_dtype(dtype) if dtype is not None else self._dtype
        shape = tuple(int(s) for s in shape)
        p = Parameter(jnp.zeros(shape, jnp.dtype(d)), trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        init = default_initializer or attr.initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        init(p)
        p.need_clip = getattr(attr, "need_clip", True)
        return p

    # ------------------------------------------------------------------ #
    # traversal
    # ------------------------------------------------------------------ #

    def parameters(self, include_sublayers=True) -> list[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{layer_prefix}.{pname}" if layer_prefix else pname
                yield full, p

    def buffers(self, include_sublayers=True) -> list[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator:
        seen = set()
        for name, layer_prefix, layer in self._walk(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                full = f"{layer_prefix}.{bname}" if layer_prefix else bname
                yield full, b

    def _walk(self, prefix="", include_sublayers=True):
        yield "", prefix, self
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{name}" if prefix else name
                for item in sub._walk(sub_prefix, True):
                    yield item

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator:
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False) -> list["Layer"]:
        out = []
        for _, _, layer in self._walk("", True):
            out.append(layer)
        return out if include_self else out[1:]

    def named_sublayers(self, prefix="", include_self=False):
        for i, (_, lp, layer) in enumerate(self._walk(prefix, True)):
            if i == 0 and not include_self:
                continue
            yield lp, layer

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    def full_name(self):
        return self._full_name

    # ------------------------------------------------------------------ #
    # modes / casting
    # ------------------------------------------------------------------ #

    def train(self):
        for layer in self.sublayers(include_self=True):
            layer.training = True
        return self

    def eval(self):
        for layer in self.sublayers(include_self=True):
            layer.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast(dtype_mod.convert_dtype(dtype))
        return self

    def float(self):
        return self.astype("float32")

    def _cast(self, d, only_float=True):
        jd = jnp.dtype(d)
        for layer in self.sublayers(include_self=True):
            layer._dtype = d
            for p in layer._parameters.values():
                if p is not None and (not only_float or dtype_mod.is_floating_point_dtype(p.dtype)):
                    p._value = p._value.astype(jd)
            for name, b in layer._buffers.items():
                if b is not None and dtype_mod.is_floating_point_dtype(b.dtype):
                    b._value = b._value.astype(jd)

    # ------------------------------------------------------------------ #
    # state dict
    # ------------------------------------------------------------------ #

    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix, include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(structured_name_prefix, include_sublayers):
            short = name.rsplit(".", 1)[-1]
            owner = self._locate_owner(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate_owner(self, qualified):
        parts = qualified.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load matching entries; returns (missing_keys, unexpected_keys) like
        the reference."""
        own = self.state_dict()
        missing, matched = [], set()
        for name, target in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            v = src._value if isinstance(src, Tensor) else jnp.asarray(src)
            if tuple(v.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: checkpoint {tuple(v.shape)} vs "
                    f"parameter {tuple(target.shape)}"
                )
            target._value = v.astype(target._value.dtype)
            matched.add(name)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------------ #
    # functional bridge (TPU-native: expose the layer tree as a pytree)
    # ------------------------------------------------------------------ #

    def functional_state(self):
        """Return ({name: param_value}, {name: buffer_value}) raw-jax pytrees."""
        params = {k: p._value for k, p in self.named_parameters()}
        bufs = {k: b._value for k, b in self.named_buffers()}
        return params, bufs

    def load_functional_state(self, params=None, buffers=None):
        if params:
            own = dict(self.named_parameters())
            for k, v in params.items():
                own[k]._value = v
        if buffers:
            own_b = dict(self.named_buffers())
            for k, v in buffers.items():
                own_b[k]._value = v

    # ------------------------------------------------------------------ #
    # hooks and call
    # ------------------------------------------------------------------ #

    def register_forward_pre_hook(self, hook):
        hid = self._hook_id
        self._hook_id += 1
        self._forward_pre_hooks[hid] = hook
        return _HookRemoveHelper(self._forward_pre_hooks, hid)

    def register_forward_post_hook(self, hook):
        hid = self._hook_id
        self._hook_id += 1
        self._forward_post_hooks[hid] = hook
        return _HookRemoveHelper(self._forward_post_hooks, hid)

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            body = repr(sub).splitlines()
            head = f"({name}): {body[0]}"
            lines.append(head)
            lines.extend("  " + b for b in body[1:])
        main = self.__class__.__name__ + "(" + extra
        if lines:
            return main + "\n  " + "\n  ".join(lines) + "\n)"
        return main + ")"


class _HookRemoveHelper:
    def __init__(self, hooks, hid):
        self._hooks, self._hid = hooks, hid

    def remove(self):
        self._hooks.pop(self._hid, None)
