"""Recurrent layers: cells, RNN/BiRNN wrappers, SimpleRNN/LSTM/GRU.

Reference surface: python/paddle/nn/layer/rnn.py (SimpleRNNCell:742,
LSTMCell:919, GRUCell:1145, RNN:1340, BiRNN:1422, RNNBase:1515,
SimpleRNN:1860, LSTM:1983, GRU:2120) and the rnn()/birnn() functionals
(rnn.py:64,388).

TPU-first design — this is NOT the reference's architecture:

* The reference runs either a per-step Python loop (dygraph) or a cuDNN
  monolith kernel (rnn_kernel.cu.cc). Here the whole time loop of one
  (layer, direction) is a SINGLE op on the autograd tape: a
  ``jax.lax.scan`` inside one ``run_op`` call. XLA compiles the scan once,
  keeps the carried state in registers/VMEM, and the MXU sees one big
  batched matmul per gate per step; the backward pass is ``jax.vjp``
  through the scan (which XLA turns into a reverse scan with
  checkpointing) — no cuDNN analog needed, no T tape nodes.
* Sequence-length masking is fused into the scan body (state carry-over via
  ``mask*new + (1-mask)*old``, the reference's _maybe_copy at rnn.py:163).
* Arbitrary user cells work too: their eager ``forward`` is traced into the
  scan body via the module-state swap (the same mechanism as
  jit.functional_call). Cells whose Python control flow cannot be traced
  fall back to the reference's eager per-step loop.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op, tracing_guard
from .. import functional as F
from .. import initializer as I
from .layers import Layer, ParamAttr
from .container import LayerList

__all__ = [
    "RNNCellBase",
    "SimpleRNNCell",
    "LSTMCell",
    "GRUCell",
    "RNN",
    "BiRNN",
    "SimpleRNN",
    "LSTM",
    "GRU",
]
# rnn()/birnn() are public too (reference rnn.py:64,388) but kept out of
# __all__ so the star-import doesn't shadow this module's name in the
# package namespace; import them as paddle.nn.layer.rnn.rnn / .birnn.


# --------------------------------------------------------------------------- #
# state pytree helpers (reference rnn.py:488 split_states / :545 concat_states)
# --------------------------------------------------------------------------- #

def split_states(states, bidirectional=False, state_components=1):
    """Split stacked [L*D, B, H] states into per-layer (per-direction) nests."""
    if state_components == 1:
        states = [states] if isinstance(states, Tensor) else list(states)
        states = states[0]
        # states: [L*D, B, H]
        layers = [states[i] for i in range(states.shape[0])]
        if not bidirectional:
            return layers
        return [(layers[2 * i], layers[2 * i + 1]) for i in range(len(layers) // 2)]
    else:
        components = [
            [comp[i] for i in range(comp.shape[0])] for comp in states
        ]
        per_slot = list(zip(*components))  # [(h_i, c_i), ...]
        if not bidirectional:
            return [tuple(s) for s in per_slot]
        return [
            (tuple(per_slot[2 * i]), tuple(per_slot[2 * i + 1]))
            for i in range(len(per_slot) // 2)
        ]


def concat_states(states, bidirectional=False, state_components=1):
    """Inverse of split_states: stack per-layer states back to [L*D, B, H]."""
    from ...tensor import stack  # local import to avoid cycles

    if bidirectional:
        flat_slots = []
        for s in states:
            flat_slots.extend([s[0], s[1]])
    else:
        flat_slots = list(states)
    if state_components == 1:
        return stack(flat_slots, axis=0)
    comps = []
    for c in range(state_components):
        comps.append(stack([slot[c] for slot in flat_slots], axis=0))
    return tuple(comps)


def _flatten_states(states):
    """Flatten a nest of Tensors to (leaves, treedef) with Tensor leaves."""
    leaves, treedef = jax.tree_util.tree_flatten(
        states, is_leaf=lambda x: isinstance(x, Tensor)
    )
    return leaves, treedef


# --------------------------------------------------------------------------- #
# cells
# --------------------------------------------------------------------------- #

class RNNCellBase(Layer):
    """Base for single-step recurrent cells (reference rnn.py:591)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        if shape is None:
            shape = self.state_shape
        if dtype is None:
            dtype = batch_ref.dtype if hasattr(batch_ref, "dtype") else "float32"
        ref_leaves, _ = _flatten_states(batch_ref)
        batch = ref_leaves[0].shape[batch_dim_idx]

        def build(s):
            full = (batch,) + tuple(int(d) for d in s)
            return Tensor(jnp.full(full, init_value, jnp.dtype(str(dtype))),
                          stop_gradient=True)

        return _map_state_shape(build, shape)

    @property
    def state_shape(self):
        raise NotImplementedError(
            f"{type(self).__name__} must define state_shape")


def _is_shape(s):
    return isinstance(s, (list, tuple)) and all(
        isinstance(d, int) for d in s)


def _map_state_shape(fn, shape):
    """Map fn over a nest whose leaves are shape tuples (tuples of ints)."""
    if _is_shape(shape):
        return fn(shape)
    return tuple(_map_state_shape(fn, s) for s in shape)


def _uniform_or(flag_attr, layer, shape, std, is_bias=False, const=0.0):
    """create_parameter with Uniform(-std, std) default; attr False =>
    constant non-trainable (reference SimpleRNNCell.__init__ pattern)."""
    if flag_attr is not False:
        return layer.create_parameter(
            shape, attr=flag_attr, is_bias=is_bias,
            default_initializer=I.Uniform(-std, std))
    p = layer.create_parameter(
        shape, attr=None, is_bias=is_bias,
        default_initializer=I.Constant(const))
    p.stop_gradient = True
    return p


class SimpleRNNCell(RNNCellBase):
    r"""h_t = act(W_ih x_t + b_ih + W_hh h_{t-1} + b_hh)
    (reference rnn.py:742)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = _uniform_or(weight_ih_attr, self,
                                     (hidden_size, input_size), std, const=1.0)
        self.weight_hh = _uniform_or(weight_hh_attr, self,
                                     (hidden_size, hidden_size), std, const=1.0)
        self.bias_ih = _uniform_or(bias_ih_attr, self,
                                   (hidden_size,), std, is_bias=True)
        self.bias_hh = _uniform_or(bias_hh_attr, self,
                                   (hidden_size,), std, is_bias=True)
        self.input_size = input_size
        self.hidden_size = hidden_size
        if activation not in ("tanh", "relu"):
            raise ValueError(
                "activation for SimpleRNNCell should be tanh or relu, "
                f"but get {activation}")
        self.activation = activation

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_h = states
        i2h = inputs.matmul(self.weight_ih, transpose_y=True) + self.bias_ih
        h2h = pre_h.matmul(self.weight_hh, transpose_y=True) + self.bias_hh
        h = (i2h + h2h).tanh() if self.activation == "tanh" else F.relu(i2h + h2h)
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.activation != "tanh":
            s += f", activation={self.activation}"
        return s


class LSTMCell(RNNCellBase):
    r"""Fused-gate LSTM cell; gate order i, f, g, o in the packed weights
    (reference rnn.py:919; proj_size per LSTMP)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        proj_size = proj_size or 0
        if proj_size >= hidden_size:
            raise ValueError("proj_size must be smaller than hidden_size")
        std = 1.0 / math.sqrt(hidden_size)
        h_in = proj_size if proj_size > 0 else hidden_size
        self.weight_ih = _uniform_or(weight_ih_attr, self,
                                     (4 * hidden_size, input_size), std,
                                     const=1.0)
        self.weight_hh = _uniform_or(weight_hh_attr, self,
                                     (4 * hidden_size, h_in), std, const=1.0)
        self.bias_ih = _uniform_or(bias_ih_attr, self,
                                   (4 * hidden_size,), std, is_bias=True)
        self.bias_hh = _uniform_or(bias_hh_attr, self,
                                   (4 * hidden_size,), std, is_bias=True)
        self.proj_size = proj_size
        if proj_size > 0:
            self.weight_ho = _uniform_or(weight_hh_attr, self,
                                         (hidden_size, proj_size), std,
                                         const=1.0)
        self.hidden_size = hidden_size
        self.input_size = input_size

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_hidden, pre_cell = states
        gates = inputs.matmul(self.weight_ih, transpose_y=True) + self.bias_ih
        gates = gates + pre_hidden.matmul(self.weight_hh, transpose_y=True) \
            + self.bias_hh
        from ...tensor import split as _split
        ig, fg, gg, og = _split(gates, 4, axis=-1)
        i = F.sigmoid(ig)
        f = F.sigmoid(fg)
        o = F.sigmoid(og)
        c = f * pre_cell + i * gg.tanh()
        h = o * c.tanh()
        if self.proj_size > 0:
            h = h.matmul(self.weight_ho)
        return h, (h, c)

    @property
    def state_shape(self):
        return ((self.proj_size or self.hidden_size,), (self.hidden_size,))

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class GRUCell(RNNCellBase):
    r"""GRU cell, reset gate applied after the hidden matmul; gate order
    r, z, c (reference rnn.py:1145)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError(
                f"hidden_size of {type(self).__name__} must be greater "
                f"than 0, but now equals to {hidden_size}")
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = _uniform_or(weight_ih_attr, self,
                                     (3 * hidden_size, input_size), std,
                                     const=1.0)
        self.weight_hh = _uniform_or(weight_hh_attr, self,
                                     (3 * hidden_size, hidden_size), std,
                                     const=1.0)
        self.bias_ih = _uniform_or(bias_ih_attr, self,
                                   (3 * hidden_size,), std, is_bias=True)
        self.bias_hh = _uniform_or(bias_hh_attr, self,
                                   (3 * hidden_size,), std, is_bias=True)
        self.hidden_size = hidden_size
        self.input_size = input_size

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        pre_hidden = states
        x_gates = inputs.matmul(self.weight_ih, transpose_y=True) + self.bias_ih
        h_gates = pre_hidden.matmul(self.weight_hh, transpose_y=True) \
            + self.bias_hh
        from ...tensor import split as _split
        x_r, x_z, x_c = _split(x_gates, 3, axis=-1)
        h_r, h_z, h_c = _split(h_gates, 3, axis=-1)
        r = F.sigmoid(x_r + h_r)
        z = F.sigmoid(x_z + h_z)
        c = (x_c + r * h_c).tanh()
        h = (pre_hidden - c) * z + c
        return h, h

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


# --------------------------------------------------------------------------- #
# the fused scan (one run_op per (layer, direction) — see module docstring)
# --------------------------------------------------------------------------- #
#
# Two paths share the same "one scan = one tape op" shape:
#  * builtin cells — pure module-level step functions; the run_op closure
#    holds only strs/ints/bools so the dispatch cache can key it by value
#    (framework/core.py _fn_token) and the scan compiles ONCE per shape.
#  * custom cells — the cell's eager forward is traced into the scan body
#    via the module-state swap. The closure holds the live cell, which is
#    uncacheable: correct, but retraced per call.

def _sig(x):
    return jax.nn.sigmoid(x)


def _simple_step(act_relu, xt, h, w_ih, w_hh, b_ih, b_hh):
    z = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jax.nn.relu(z) if act_relu else jnp.tanh(z)


def _lstm_step(xt, h, c, w_ih, w_hh, b_ih, b_hh, w_ho=None):
    gates = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c2 = _sig(f) * c + _sig(i) * jnp.tanh(g)
    h2 = _sig(o) * jnp.tanh(c2)
    if w_ho is not None:
        h2 = h2 @ w_ho
    return h2, c2


def _gru_step(xt, h, w_ih, w_hh, b_ih, b_hh):
    xg = xt @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = _sig(x_r + h_r)
    z = _sig(x_z + h_z)
    c = jnp.tanh(x_c + r * h_c)
    return (h - c) * z + c


def _mask_merge(mt, new, old):
    m = mt.reshape(mt.shape + (1,) * (new.ndim - 1))
    return m * new + (1 - m) * old


def _builtin_spec(cell):
    """(kind, params, act_relu) for unmodified builtin cells."""
    t = type(cell)
    if t is SimpleRNNCell:
        return ("simple",
                [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh],
                cell.activation == "relu")
    if t is LSTMCell:
        ps = [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh]
        if cell.proj_size > 0:
            ps.append(cell.weight_ho)
        return ("lstm", ps, False)
    if t is GRUCell:
        return ("gru",
                [cell.weight_ih, cell.weight_hh, cell.bias_ih, cell.bias_hh],
                False)
    return None


def _scan_rnn(cell, inputs, initial_states, sequence_length, time_major,
              is_reverse, kwargs):
    """One lax.scan over time as a single tape op. Raises jax trace errors
    for cells with untraceable Python control flow (caller falls back)."""
    state_leaves, treedef = _flatten_states(initial_states)
    n_s = len(state_leaves)
    has_mask = sequence_length is not None
    spec = _builtin_spec(cell) if not kwargs else None

    if spec is not None:
        kind, param_tensors, act_relu = spec

        def fn(x, *rest):
            states0 = rest[:n_s]
            seq = rest[n_s] if has_mask else None
            pvals = rest[n_s + 1:] if has_mask else rest[n_s:]
            xs = x if time_major else jnp.moveaxis(x, 1, 0)  # [T, B, I]
            T = xs.shape[0]
            if has_mask:
                m = (jnp.arange(T)[None, :] < seq[:, None]).astype(xs.dtype)
                scan_xs = (xs, jnp.moveaxis(m, 1, 0))
            else:
                scan_xs = (xs,)

            def step(carry, xt_m):
                xt = xt_m[0]
                if kind == "simple":
                    h = _simple_step(act_relu, xt, carry[0], *pvals)
                    new = (h,)
                elif kind == "lstm":
                    h, c = _lstm_step(xt, carry[0], carry[1], *pvals)
                    new = (h, c)
                else:
                    h = _gru_step(xt, carry[0], *pvals)
                    new = (h,)
                out = new[0]  # step outputs stay unmasked (reference
                # rnn.py:176 only _maybe_copy's the STATES); mask gates
                # the carry so padded steps don't advance the state.
                if has_mask:
                    mt = xt_m[1]
                    new = tuple(_mask_merge(mt, n, o)
                                for n, o in zip(new, carry))
                return new, out

            final, ys = jax.lax.scan(step, tuple(states0), scan_xs,
                                     reverse=is_reverse)
            ys = ys if time_major else jnp.moveaxis(ys, 0, 1)
            return (ys,) + tuple(final)
    else:
        from ...jit import _ModuleState  # lazy: jit imports nn at module load

        state = _ModuleState(cell)
        param_items = sorted(state.params.items())
        param_names = [k for k, _ in param_items]
        param_tensors = [p for _, p in param_items]
        out_tree = []  # output treedef, captured at trace time; this path
        # is never dispatch-cached (closure holds the live cell), so fn —
        # and the capture — runs on every call.

        def fn(x, *rest):
            states0 = rest[:n_s]
            seq = rest[n_s] if has_mask else None
            pvals = rest[n_s + 1:] if has_mask else rest[n_s:]
            xs = x if time_major else jnp.moveaxis(x, 1, 0)  # [T, B, ...]
            T = xs.shape[0]
            if has_mask:
                m = (jnp.arange(T)[None, :] < seq[:, None]).astype(xs.dtype)
                scan_xs = (xs, jnp.moveaxis(m, 1, 0))
            else:
                scan_xs = (xs,)

            saved = state.swap_in(dict(zip(param_names, pvals)), None)
            try:
                def step(carry, xt_m):
                    xt = xt_m[0]
                    st = jax.tree_util.tree_unflatten(
                        treedef, [Tensor(c) for c in carry])
                    with tracing_guard(True):
                        out, new_st = cell(Tensor(xt), st, **kwargs)
                    new_leaves = [
                        t._value for t in _flatten_states(new_st)[0]]
                    if has_mask:
                        mt = xt_m[1]
                        new_leaves = [_mask_merge(mt, n, o)
                                      for n, o in zip(new_leaves, carry)]
                    o_leaves, o_tree = _flatten_states(out)
                    if not out_tree:
                        out_tree.append(o_tree)
                    return tuple(new_leaves), tuple(
                        t._value for t in o_leaves)

                final, ys = jax.lax.scan(step, tuple(states0), scan_xs,
                                         reverse=is_reverse)
            finally:
                state.restore(saved)
            ys = [y if time_major else jnp.moveaxis(y, 0, 1) for y in ys]
            return tuple(ys) + tuple(final)

    op_inputs = [inputs] + list(state_leaves)
    if has_mask:
        op_inputs.append(sequence_length)
    op_inputs.extend(param_tensors)
    out = run_op("rnn_scan", fn, op_inputs)
    out = list(out) if isinstance(out, tuple) else [out]
    n_out = len(out) - n_s
    out_leaves, final_leaves = out[:n_out], out[n_out:]
    # outputs mirror the structure of one step's output; builtin cells (and
    # any cell returning a single Tensor) yield a Tensor, custom cells with
    # nested outputs get their structure back from the trace-time capture.
    if n_out == 1:
        outputs = out_leaves[0]
    elif spec is None and out_tree:
        outputs = jax.tree_util.tree_unflatten(out_tree[0], out_leaves)
    else:
        outputs = tuple(out_leaves)
    final_states = jax.tree_util.tree_unflatten(treedef, final_leaves)
    return outputs, final_states


def _rnn_eager_loop(cell, inputs, initial_states, sequence_length,
                    time_major, is_reverse, kwargs):
    """Reference dygraph path (rnn.py:176): per-step Python loop. Used only
    when the cell cannot be traced into the fused scan."""
    from ...tensor import stack

    time_axis = 0 if time_major else 1
    T = inputs.shape[time_axis]
    states = initial_states
    mask = None
    if sequence_length is not None:
        ar = jnp.arange(T)[None, :] < sequence_length._value[:, None]
        mask = ar.astype(inputs._value.dtype)  # [B, T]

    order = range(T - 1, -1, -1) if is_reverse else range(T)
    outputs = []
    for i in order:
        xt = inputs[:, i] if not time_major else inputs[i]
        out, new_states = cell(xt, states, **kwargs)
        if mask is not None:
            mt = Tensor(mask[:, i])
            sl, td = _flatten_states(new_states)
            ol, _ = _flatten_states(states)
            merged = []
            for n, o in zip(sl, ol):
                m = mt.reshape([-1] + [1] * (len(n.shape) - 1))
                merged.append(m * n + (1.0 - m) * o)
            new_states = jax.tree_util.tree_unflatten(td, merged)
            if td.num_leaves == 1 and isinstance(new_states, (tuple, list)):
                new_states = new_states[0]
        states = new_states
        outputs.append(out)
    if is_reverse:
        outputs = outputs[::-1]
    outputs = stack(outputs, axis=time_axis)
    return outputs, states


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run a cell over the time dimension (reference rnn.py:64).

    inputs: [B, T, ...] (or [T, B, ...] when time_major). Returns
    (outputs, final_states).
    """
    if initial_states is None:
        initial_states = cell.get_initial_states(
            inputs, batch_dim_idx=1 if time_major else 0)
    # Both eager and under an enclosing trace (to_static / compiled train
    # step) the fused scan is the path: run_op executes the scan fn on the
    # tracers, so the loop lowers to ONE lax.scan of the outer program —
    # never an unrolled per-step trace.
    try:
        return _scan_rnn(cell, inputs, initial_states, sequence_length,
                         time_major, is_reverse, kwargs)
    except Exception as e:  # noqa: BLE001 — trace-ineligible cells only
        from ...jit import _is_trace_ineligible
        if not _is_trace_ineligible(e):
            raise
        return _rnn_eager_loop(cell, inputs, initial_states, sequence_length,
                               time_major, is_reverse, kwargs)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """Bidirectional pass: two fused scans, outputs concat on the feature
    axis (reference rnn.py:388)."""
    from ...tensor import concat

    if initial_states is None:
        states_fw = None
        states_bw = None
    else:
        states_fw, states_bw = initial_states
    out_fw, st_fw = rnn(cell_fw, inputs, states_fw, sequence_length,
                        time_major, False, **kwargs)
    out_bw, st_bw = rnn(cell_bw, inputs, states_bw, sequence_length,
                        time_major, True, **kwargs)
    outputs = concat([out_fw, out_bw], axis=-1)
    return outputs, (st_fw, st_bw)


# --------------------------------------------------------------------------- #
# layer wrappers
# --------------------------------------------------------------------------- #

class RNN(Layer):
    """Wrap a cell into a sequence layer (reference rnn.py:1340)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        if not hasattr(self.cell, "call") and not hasattr(self.cell, "forward"):
            raise ValueError("RNN cell must define forward")
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        return rnn(self.cell, inputs, initial_states, sequence_length,
                   self.time_major, self.is_reverse, **kwargs)


class BiRNN(Layer):
    """Forward + backward cells over one sequence (reference rnn.py:1422)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        if cell_fw.input_size != cell_bw.input_size:
            raise ValueError(
                "input size of forward and backward cells must match")
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if isinstance(initial_states, (list, tuple)) \
                and len(initial_states) != 2:
            raise ValueError("initial_states must be a pair (fw, bw)")
        return birnn(self.cell_fw, self.cell_bw, inputs, initial_states,
                     sequence_length, self.time_major, **kwargs)


class RNNBase(LayerList):
    """Multi-layer (optionally bidirectional) recurrent net
    (reference rnn.py:1515). One fused scan per (layer, direction); dropout
    between layers; stacked [L*D, B, H] state interface."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0):
        super().__init__()
        bidirectional_list = ("bidirectional", "bidirect")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.dropout = dropout
        self.num_directions = 2 if direction in bidirectional_list else 1
        self.time_major = time_major
        self.num_layers = num_layers
        self.state_components = 2 if mode == "LSTM" else 1
        self.proj_size = proj_size or 0
        if self.proj_size > 0 and mode != "LSTM":
            raise ValueError("proj_size only supported for LSTM")

        kwargs = {
            "weight_ih_attr": weight_ih_attr,
            "weight_hh_attr": weight_hh_attr,
            "bias_ih_attr": bias_ih_attr,
            "bias_hh_attr": bias_hh_attr,
        }
        if mode == "LSTM":
            rnn_cls = LSTMCell
            kwargs["proj_size"] = proj_size
        elif mode == "GRU":
            rnn_cls = GRUCell
        elif mode == "RNN_RELU":
            rnn_cls = SimpleRNNCell
            kwargs["activation"] = "relu"
        elif mode == "RNN_TANH":
            rnn_cls = SimpleRNNCell
            kwargs["activation"] = "tanh"
        else:
            raise ValueError(f"unknown RNN mode {mode!r}")

        in_size = self.proj_size or hidden_size
        if direction == "forward":
            cell = rnn_cls(input_size, hidden_size, **kwargs)
            self.append(RNN(cell, False, time_major))
            for _ in range(1, num_layers):
                cell = rnn_cls(in_size, hidden_size, **kwargs)
                self.append(RNN(cell, False, time_major))
        elif direction in bidirectional_list:
            cell_fw = rnn_cls(input_size, hidden_size, **kwargs)
            cell_bw = rnn_cls(input_size, hidden_size, **kwargs)
            self.append(BiRNN(cell_fw, cell_bw, time_major))
            for _ in range(1, num_layers):
                cell_fw = rnn_cls(2 * in_size, hidden_size, **kwargs)
                cell_bw = rnn_cls(2 * in_size, hidden_size, **kwargs)
                self.append(BiRNN(cell_fw, cell_bw, time_major))
        else:
            raise ValueError(
                "direction should be forward or bidirect (or bidirectional), "
                f"received direction = {direction}")

        # Expose paddle-style flat aliases (weight_ih_l0, ... , *_reverse) so
        # user code that pokes at them keeps working. Set via object.__setattr__
        # on purpose: state_dict keys stay the structural "0.cell.weight_ih"
        # form (no duplicate entries), matching this framework's checkpoints.
        for layer_i in range(num_layers):
            sub = self[layer_i]
            cells = [sub.cell] if self.num_directions == 1 \
                else [sub.cell_fw, sub.cell_bw]
            for d, c in enumerate(cells):
                suffix = "_reverse" if d == 1 else ""
                object.__setattr__(
                    self, f"weight_ih_l{layer_i}{suffix}", c.weight_ih)
                object.__setattr__(
                    self, f"weight_hh_l{layer_i}{suffix}", c.weight_hh)
                if bias_ih_attr is not False:
                    object.__setattr__(
                        self, f"bias_ih_l{layer_i}{suffix}", c.bias_ih)
                if bias_hh_attr is not False:
                    object.__setattr__(
                        self, f"bias_hh_l{layer_i}{suffix}", c.bias_hh)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        batch_index = 1 if self.time_major else 0
        dtype = inputs.dtype
        if initial_states is None:
            batch = inputs.shape[batch_index]
            dims = ([self.proj_size or self.hidden_size], [self.hidden_size])
            initial_states = tuple(
                Tensor(jnp.zeros(
                    (self.num_layers * self.num_directions, batch, *dims[i]),
                    jnp.dtype(str(dtype))), stop_gradient=True)
                for i in range(self.state_components))
        elif isinstance(initial_states, Tensor):
            initial_states = [initial_states]

        states = split_states(initial_states, self.num_directions == 2,
                              self.state_components)
        final_states = []
        outputs = inputs
        for i, rnn_layer in enumerate(self):
            if i > 0:
                outputs = F.dropout(outputs, self.dropout,
                                    training=self.training,
                                    mode="upscale_in_train")
            outputs, final_state = rnn_layer(outputs, states[i],
                                             sequence_length)
            final_states.append(final_state)

        final_states = concat_states(final_states, self.num_directions == 2,
                                     self.state_components)
        return outputs, final_states

    def extra_repr(self):
        s = f"{self.input_size}, {self.hidden_size}"
        if self.num_layers != 1:
            s += f", num_layers={self.num_layers}"
        if self.time_major:
            s += f", time_major={self.time_major}"
        if self.dropout:
            s += f", dropout={self.dropout}"
        return s


class SimpleRNN(RNNBase):
    """Multi-layer Elman RNN (reference rnn.py:1860)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        if activation == "tanh":
            mode = "RNN_TANH"
        elif activation == "relu":
            mode = "RNN_RELU"
        else:
            raise ValueError(f"Unknown activation '{activation}'")
        self.activation = activation
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """Multi-layer LSTM (reference rnn.py:1983)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0,
                 name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr,
                         proj_size)


class GRU(RNNBase):
    """Multi-layer GRU (reference rnn.py:2120)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
