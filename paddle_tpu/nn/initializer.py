"""Parameter initializers (reference: python/paddle/nn/initializer/).

Each initializer is a callable applied to a Parameter in place; values come
from jax.random draws off the global key.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd
from ..framework.core import Tensor

__all__ = [
    "Initializer",
    "Constant",
    "Normal",
    "TruncatedNormal",
    "Uniform",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "Orthogonal",
    "Dirac",
    "calculate_gain",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


# One jitted executable per (shape, dtype) — init of a large model is
# thousands of tiny ops, and each eager op over the TPU tunnel pays a
# compile+RPC round trip; sampling+affine+cast fused into a single cached
# program makes it one.
from functools import partial as _partial


@_partial(jax.jit, static_argnames=("shape", "dtype"))
def _sample_normal(key, mean, std, shape, dtype):
    return (mean + std * jax.random.normal(key, shape, jnp.float32)).astype(dtype)


@_partial(jax.jit, static_argnames=("shape", "dtype"))
def _sample_truncated(key, mean, std, a, b, shape, dtype):
    v = jax.random.truncated_normal(key, a, b, shape, jnp.float32)
    return (mean + std * v).astype(dtype)


@_partial(jax.jit, static_argnames=("shape", "dtype"))
def _sample_uniform(key, low, high, shape, dtype):
    return jax.random.uniform(key, shape, jnp.float32, low, high).astype(dtype)


@_partial(jax.jit, static_argnames=("shape", "dtype"))
def _full_value(value, shape, dtype):
    return jnp.full(shape, value, dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv: [out_c, in_c, *kernel] — matches the reference's fan computation
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param: Tensor, block=None):
        raise NotImplementedError

    def _set(self, param, value):
        param._value = value.astype(param._value.dtype)
        return param


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block=None):
        return self._set(
            param, _full_value(self.value, tuple(param.shape), param.dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, param, block=None):
        v = _sample_normal(rnd.next_key(), self.mean, self.std,
                           tuple(param.shape), param.dtype)
        return self._set(param, v)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, param, block=None):
        v = _sample_truncated(rnd.next_key(), self.mean, self.std, self.a,
                              self.b, tuple(param.shape), param.dtype)
        return self._set(param, v)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, param, block=None):
        v = _sample_uniform(rnd.next_key(), self.low, self.high,
                            tuple(param.shape), param.dtype)
        return self._set(param, v)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        v = _sample_normal(rnd.next_key(), 0.0, std, tuple(param.shape),
                           param.dtype)
        return self._set(param, v)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, param, block=None):
        fi, fo = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        v = _sample_uniform(rnd.next_key(), -limit, limit, tuple(param.shape),
                            param.dtype)
        return self._set(param, v)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        v = _sample_normal(rnd.next_key(), 0.0, std, tuple(param.shape),
                           param.dtype)
        return self._set(param, v)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, param, block=None):
        fi, _ = _fans(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        v = _sample_uniform(rnd.next_key(), -limit, limit, tuple(param.shape),
                            param.dtype)
        return self._set(param, v)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, param, block=None):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        return self._set(param, jnp.asarray(v))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, param, block=None):
        shape = tuple(param.shape)
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jax.random.normal(rnd.next_key(), (max(rows, cols), min(rows, cols)), jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return self._set(param, self.gain * q[:rows, :cols].reshape(shape))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, param, block=None):
        shape = tuple(param.shape)
        v = np.zeros(shape, np.float32)
        out_per_group = shape[0] // self.groups
        minc = min(out_per_group, shape[1])
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(minc):
                v[(g * out_per_group + i, i) + centers] = 1.0
        return self._set(param, jnp.asarray(v))


# reference-style aliases
constant = Constant
normal = Normal
uniform = Uniform
