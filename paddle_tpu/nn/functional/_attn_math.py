"""Shared dense-attention math used by every non-Pallas attention entry point
(scaled_dot_product_attention fallback, MMHA, paged block attention, varlen
attention, FusedMultiTransformer). One implementation of the f32-softmax
masked attention so mask constants / dtype policy can't drift between them."""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k, v, num_q_heads, head_axis=2):
    """GQA/MQA: repeat kv heads up to num_q_heads along head_axis."""
    hkv = k.shape[head_axis]
    if hkv != num_q_heads:
        rep = num_q_heads // hkv
        k = jnp.repeat(k, rep, axis=head_axis)
        v = jnp.repeat(v, rep, axis=head_axis)
    return k, v


def masked_attention(q, k, v, keep=None, add_mask=None, scale=None):
    """q [B, Sq, H, D], k/v [B, Sk, H(kv), D] -> [B, Sq, H, D].

    keep: broadcastable bool to [B, H, Sq, Sk] (True = attend).
    add_mask: additive f32 mask broadcastable to [B, H, Sq, Sk].
    Softmax in f32, output cast back to q.dtype.
    """
    D = q.shape[-1]
    k, v = repeat_kv(k, v, q.shape[2])
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    logits = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    if keep is not None:
        logits = jnp.where(keep, logits, NEG_INF)
    if add_mask is not None:
        logits = logits + add_mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bthd->bshd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def bottom_right_causal_keep(sq, sk, q_lens=None, kv_lens=None):
    """Bottom-right-aligned causal keep mask (the flash-attn convention this
    repo uses everywhere: the LAST query row aligns with the last valid key).

    Returns bool [B, 1, Sq, Sk] when lens given, else [1, 1, Sq, Sk].
    """
    qpos = jnp.arange(sq)[:, None]
    kpos = jnp.arange(sk)[None, :]
    if q_lens is None and kv_lens is None:
        return (kpos <= qpos + (sk - sq))[None, None]
    q_lens = q_lens.reshape(-1, 1, 1).astype(jnp.int32)
    kv_lens = kv_lens.reshape(-1, 1, 1).astype(jnp.int32)
    causal = kpos[None] <= qpos[None] + (kv_lens - q_lens)
    valid = kpos[None] < kv_lens
    return (causal & valid)[:, None]
