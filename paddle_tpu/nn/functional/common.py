"""Common functionals: linear, dropout, embedding, one_hot, interpolate, etc.
(reference: python/paddle/nn/functional/common.py, input.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as rnd
from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "linear",
    "dropout",
    "dropout2d",
    "dropout3d",
    "alpha_dropout",
    "embedding",
    "one_hot",
    "label_smooth",
    "pad",
    "interpolate",
    "upsample",
    "unfold",
    "fold",
    "cosine_similarity",
    "pixel_shuffle",
    "pixel_unshuffle",
    "channel_shuffle",
    "bilinear",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Reference stores weight [in, out]
    (python/paddle/nn/functional/common.py linear); bf16/f16 accumulate in f32
    on the MXU via preferred_element_type."""

    if bias is None:
        def fn(a, w):
            acc = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else None
            return jnp.matmul(a, w, preferred_element_type=acc).astype(
                jnp.promote_types(a.dtype, w.dtype)
            )

        return run_op("linear", fn, [_t(x), _t(weight)])

    def fnb(a, w, b):
        acc = jnp.float32 if a.dtype in (jnp.bfloat16, jnp.float16) else None
        out = jnp.matmul(a, w, preferred_element_type=acc).astype(
            jnp.promote_types(a.dtype, w.dtype)
        )
        return out + b

    return run_op("linear", fnb, [_t(x), _t(weight), _t(bias)])


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    xx = _t(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return run_op("dropout_scale", lambda a: a * (1.0 - p), [xx])
        return xx
    if p == 1.0:
        return run_op("dropout_all", lambda a: jnp.zeros_like(a), [xx])
    def fn(a, key):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros((), a.dtype)).astype(a.dtype)
        return jnp.where(keep, a, jnp.zeros((), a.dtype))

    return run_op("dropout", fn, [xx, rnd.rng_tensor()])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    xx = _t(x)
    if not training or p == 0.0:
        return xx
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(a, key):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return run_op("alpha_dropout", fn, [xx, rnd.rng_tensor()])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference: python/paddle/nn/functional/input.py embedding. padding_idx
    rows contribute zero gradient (masked lookup)."""

    def fn(ids, w):
        ids = ids.astype(jnp.int32)
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            mask = (ids != padding_idx)[..., None].astype(out.dtype)
            out = out * mask
        return out

    return run_op("embedding", fn, [_t(x), _t(weight)])


def one_hot(x, num_classes, name=None):
    return run_op(
        "one_hot",
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), int(num_classes), dtype=jnp.float32),
        [_t(x)],
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is None:
        def fn(l):
            k = l.shape[-1]
            return (1 - epsilon) * l + epsilon / k

        return run_op("label_smooth", fn, [_t(label)])

    def fnp(l, pd):
        return (1 - epsilon) * l + epsilon * pd

    return run_op("label_smooth", fnp, [_t(label), _t(prior_dist)])


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ...tensor.manipulation import pad as _pad

    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    """reference: python/paddle/nn/functional/common.py interpolate — via
    jax.image.resize (nearest / bilinear / bicubic / trilinear / area)."""
    xx = _t(x)
    nd = xx.ndim
    channels_last = not data_format.startswith("NC")
    spatial = list(range(2, nd)) if not channels_last else list(range(1, nd - 1))
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
        out_sizes = [int(xx.shape[ax] * f) for ax, f in zip(spatial, sf)]

    jmode = {
        "nearest": "nearest",
        "bilinear": "bilinear",
        "bicubic": "bicubic",
        "trilinear": "trilinear",
        "linear": "linear",
        "area": "linear",
    }[mode]

    def fn(a):
        out_shape = list(a.shape)
        for ax, s in zip(spatial, out_sizes):
            out_shape[ax] = s
        return jax.image.resize(a, tuple(out_shape), method=jmode).astype(a.dtype)

    return run_op("interpolate", fn, [xx])


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference: unfold op). Output [N, C*kh*kw, L]."""
    xx = _t(x)
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        oh = (a.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
        ow = (a.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            a, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        return patches.reshape(n, c * kh * kw, oh * ow)

    return run_op("unfold", fn, [xx])


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    xx = _t(x)
    oh, ow = output_sizes
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    p = paddings if isinstance(paddings, int) else paddings[0]

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        ph = oh + 2 * p
        pw = ow + 2 * p
        nh = (ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (pw - (dw * (kw - 1) + 1)) // sw + 1
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        a5 = a.reshape(n, c, kh, kw, nh, nw)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + nh * sh:sh, wj:wj + nw * sw:sw].add(a5[:, :, i, j])
        return out[:, :, p:p + oh, p:p + ow]

    return run_op("fold", fn, [xx])


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)

    return run_op("cosine_similarity", fn, [_t(x1), _t(x2)])


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        oc = c // (r * r)
        a = a.reshape(n, oc, r, r, h, w)
        a = a.transpose(0, 1, 4, 2, 5, 3)
        return a.reshape(n, oc, h * r, w * r)

    return run_op("pixel_shuffle", fn, [_t(x)])


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = a.transpose(0, 1, 3, 5, 2, 4)
        return a.reshape(n, c * r * r, h // r, w // r)

    return run_op("pixel_unshuffle", fn, [_t(x)])


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(a):
        n, c, h, w = a.shape
        a = a.reshape(n, g, c // g, h, w)
        return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)

    return run_op("channel_shuffle", fn, [_t(x)])


def bilinear(x1, x2, weight, bias=None, name=None):
    ins = [_t(x1), _t(x2), _t(weight)]
    has_bias = bias is not None
    if has_bias:
        ins.append(_t(bias))

    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    return run_op("bilinear", fn, ins)
