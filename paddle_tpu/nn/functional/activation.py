"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All lower to jax.nn / jnp — XLA fuses these into neighbouring matmuls on TPU,
which is exactly what the reference's fused_bias_act epilogue kernels do by hand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "rrelu",
    "relu",
    "relu6",
    "relu_",
    "gelu",
    "leaky_relu",
    "elu",
    "selu",
    "celu",
    "silu",
    "swish",
    "mish",
    "hardswish",
    "hardsigmoid",
    "hardtanh",
    "hardshrink",
    "softshrink",
    "tanhshrink",
    "softplus",
    "softsign",
    "prelu",
    "softmax",
    "log_softmax",
    "sigmoid",
    "log_sigmoid",
    "tanh",
    "glu",
    "gumbel_softmax",
    "maxout",
    "thresholded_relu",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def relu(x, name=None):
    return run_op("relu", jax.nn.relu, [_t(x)])


def relu_(x, name=None):
    out = relu(x)
    if isinstance(x, Tensor):
        return x._inplace_update(out)
    return out


def relu6(x, name=None):
    return run_op("relu6", jax.nn.relu6, [_t(x)])


def gelu(x, approximate=False, name=None):
    return run_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), [_t(x)])


def leaky_relu(x, negative_slope=0.01, name=None):
    return run_op(
        "leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), [_t(x)]
    )


def elu(x, alpha=1.0, name=None):
    return run_op("elu", lambda a: jax.nn.elu(a, alpha), [_t(x)])


def selu(
    x,
    scale=1.0507009873554805,
    alpha=1.6732632423543772,
    name=None,
):
    return run_op(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
        [_t(x)],
    )


def celu(x, alpha=1.0, name=None):
    return run_op("celu", lambda a: jax.nn.celu(a, alpha), [_t(x)])


def silu(x, name=None):
    return run_op("silu", jax.nn.silu, [_t(x)])


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return run_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), [_t(x)])


def hardswish(x, name=None):
    return run_op("hardswish", jax.nn.hard_swish, [_t(x)])


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return run_op(
        "hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), [_t(x)]
    )


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return run_op("hardtanh", lambda a: jnp.clip(a, min, max), [_t(x)])


def hardshrink(x, threshold=0.5, name=None):
    return run_op(
        "hardshrink",
        lambda a: jnp.where(jnp.abs(a) > threshold, a, jnp.zeros((), a.dtype)),
        [_t(x)],
    )


def softshrink(x, threshold=0.5, name=None):
    return run_op(
        "softshrink",
        lambda a: jnp.where(
            a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)
        ).astype(a.dtype),
        [_t(x)],
    )


def tanhshrink(x, name=None):
    return run_op("tanhshrink", lambda a: a - jnp.tanh(a), [_t(x)])


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return run_op(
        "softplus",
        lambda a: jnp.where(
            beta * a > threshold, a, (1.0 / beta) * jax.nn.softplus(beta * a)
        ),
        [_t(x)],
    )


def softsign(x, name=None):
    return run_op("softsign", jax.nn.soft_sign, [_t(x)])


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            slope = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            slope = w.reshape(shape)
        return jnp.where(a > 0, a, slope * a)

    return run_op("prelu", fn, [_t(x), _t(weight)])


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...framework import dtype as dtype_mod

            a = a.astype(jnp.dtype(dtype_mod.convert_dtype(dtype)))
        return jax.nn.softmax(a, axis=axis)

    return run_op("softmax", fn, [_t(x)])


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...framework import dtype as dtype_mod

            a = a.astype(jnp.dtype(dtype_mod.convert_dtype(dtype)))
        return jax.nn.log_softmax(a, axis=axis)

    return run_op("log_softmax", fn, [_t(x)])


def sigmoid(x, name=None):
    return run_op("sigmoid", jax.nn.sigmoid, [_t(x)])


def log_sigmoid(x, name=None):
    return run_op("log_sigmoid", jax.nn.log_sigmoid, [_t(x)])


def tanh(x, name=None):
    return run_op("tanh", jnp.tanh, [_t(x)])


def glu(x, axis=-1, name=None):
    return run_op("glu", lambda a: jax.nn.glu(a, axis=axis), [_t(x)])


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as rnd

    def fn(a, key):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            # straight-through: one-hot forward, soft gradient
            oh = (y == jnp.max(y, axis=axis, keepdims=True)).astype(y.dtype)
            return oh + y - jax.lax.stop_gradient(y)
        return y

    return run_op("gumbel_softmax", fn, [_t(x), rnd.rng_tensor()])


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        shp = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(shp), axis=ax + 1)

    return run_op("maxout", fn, [_t(x)])


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return run_op(
        "thresholded_relu",
        lambda a: jnp.where(a > threshold, a, jnp.asarray(value, a.dtype)),
        [_t(x)],
    )


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    """Randomized leaky ReLU (reference nn/functional/activation.py rrelu;
    kernel rrelu_kernel.cu). Training: per-element negative slope ~
    U(lower, upper); inference: fixed slope (lower+upper)/2. The key rides
    in as a tagged input (framework.random.rng_tensor) so the op stays
    dispatch-cacheable and SOT-replayable."""
    if not 0 <= lower <= upper <= 1:
        raise ValueError(
            f"rrelu expects 0 <= lower <= upper <= 1, got {lower}, {upper}")
    if not training:
        slope = (lower + upper) / 2.0
        return run_op(
            "rrelu_eval",
            lambda a: jnp.where(a >= 0, a, a * jnp.asarray(slope, a.dtype)),
            [_t(x)])
    from ...framework import random as rnd

    def fn(a, key):
        s = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
        return jnp.where(a >= 0, a, a * s)

    return run_op("rrelu_train", fn, [_t(x), rnd.rng_tensor()])
