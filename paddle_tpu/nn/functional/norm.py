"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

layer_norm / rms_norm here are the jnp reference paths; the fused Pallas
kernels (paddle_tpu.ops.pallas) override them for the shapes that matter —
the analog of the reference's fused_layernorm / rms_norm CUDA kernels
(paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu, gpu/rms_norm_kernel.cu).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "normalize",
    "layer_norm",
    "batch_norm",
    "instance_norm",
    "group_norm",
    "local_response_norm",
    "rms_norm",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _fused_norm_route() -> bool:
    """True when last-axis norms should run the fused Pallas kernels
    (paddle_tpu.ops.pallas.fused_norm). Read ONCE per call at the eager
    entry and captured into the traced closure — the dispatch cache keys on
    it, and under jit the choice is frozen at trace time, so a
    PADDLE_TPU_FUSED_NORM flip mid-run can never mix the kernel forward
    with the composite backward (the PR-7 safe-softmax capture rule)."""
    from ...ops.pallas.fused_norm import fused_norm_on

    if not fused_norm_on():
        return False
    from .flash_attention import _use_pallas_kernel

    return _use_pallas_kernel()


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return run_op("normalize", fn, [_t(x)])


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape)
    ins = [_t(x)]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(_t(weight))
    if has_b:
        ins.append(_t(bias))

    fused = n_axes == 1 and _fused_norm_route()

    def fn(a, *rest):
        i = 0
        w = rest[i] if has_w else None
        i += has_w
        b = rest[i] if has_b else None
        if (fused and a.ndim >= 2
                and (w is None or w.ndim == 1)
                and (b is None or b.ndim == 1)):
            from ...ops.pallas.fused_norm import layer_norm_fwd

            return layer_norm_fwd(a, w, b, epsilon)
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        x32 = a.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(a.dtype)

    return run_op("layer_norm", fn, ins)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: python/paddle/incubate/nn/functional/fused_rms_norm.py:59).
    Stats in f32 regardless of input dtype, like the reference kernel. On
    TPU (and under the Pallas interpreter) this routes to the fused Pallas
    kernel unless PADDLE_TPU_FUSED_NORM=0 selects the lax composite."""
    ins = [_t(x)]
    has_w = weight is not None
    if has_w:
        ins.append(_t(weight))
    fused = _fused_norm_route()

    def fn(a, *rest):
        w = rest[0] if rest else None
        if fused and a.ndim >= 2 and (w is None or w.ndim == 1):
            from ...ops.pallas.fused_norm import rms_norm_fwd

            return rms_norm_fwd(a, w, epsilon)
        x32 = a.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            out = out * w.astype(jnp.float32)
        return out.astype(a.dtype)

    return run_op("rms_norm", fn, ins)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    """reference: python/paddle/nn/functional/norm.py batch_norm. In training,
    running stats are updated in place on the buffer handles (eager); the jit
    path threads buffers functionally via Layer.functional_state."""
    xx = _t(x)
    rm, rv = _t(running_mean), _t(running_var)
    channels_last = not data_format.startswith("NC")
    ch_axis = -1 if channels_last else 1
    use_batch = training and not use_global_stats

    ins = [xx, rm, rv]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(_t(weight))
    if has_b:
        ins.append(_t(bias))

    def fn(a, m, v, *rest):
        axes = tuple(i for i in range(a.ndim) if i != ch_axis % a.ndim)
        shape = [1] * a.ndim
        shape[ch_axis % a.ndim] = a.shape[ch_axis % a.ndim]
        if use_batch:
            x32 = a.astype(jnp.float32)
            bm = jnp.mean(x32, axis=axes)
            bv = jnp.var(x32, axis=axes)
            mean, var = bm, bv
        else:
            mean, var = m.astype(jnp.float32), v.astype(jnp.float32)
        out = (a.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape) + epsilon
        )
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape).astype(jnp.float32)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape).astype(jnp.float32)
        if use_batch:
            return out.astype(a.dtype), mean, var
        return out.astype(a.dtype), m.astype(jnp.float32), v.astype(jnp.float32)

    out, bm, bv = run_op("batch_norm", fn, ins)
    if use_batch:
        # momentum update of running stats (paddle: r = m*r + (1-m)*batch)
        new_m = momentum * rm._value.astype(jnp.float32) + (1 - momentum) * bm._value
        new_v = momentum * rv._value.astype(jnp.float32) + (1 - momentum) * bv._value
        rm._value = new_m.astype(rm._value.dtype)
        rv._value = new_v.astype(rv._value.dtype)
        if isinstance(running_mean, Tensor) and running_mean is not rm:
            running_mean._value = rm._value
        if isinstance(running_var, Tensor) and running_var is not rv:
            running_var._value = rv._value
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    ins = [_t(x)]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(_t(weight))
    if has_b:
        ins.append(_t(bias))

    def fn(a, *rest):
        axes = tuple(range(2, a.ndim))
        x32 = a.astype(jnp.float32)
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.var(x32, axis=axes, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape).astype(jnp.float32)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape).astype(jnp.float32)
        return out.astype(a.dtype)

    return run_op("instance_norm", fn, ins)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW", name=None):
    g = int(num_groups)
    channels_last = not data_format.startswith("NC")
    ins = [_t(x)]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        ins.append(_t(weight))
    if has_b:
        ins.append(_t(bias))

    def fn(a, *rest):
        if channels_last:
            a_ncx = jnp.moveaxis(a, -1, 1)
        else:
            a_ncx = a
        n, c = a_ncx.shape[:2]
        spatial = a_ncx.shape[2:]
        x32 = a_ncx.astype(jnp.float32).reshape(n, g, c // g, *spatial)
        axes = tuple(range(2, x32.ndim))
        mean = jnp.mean(x32, axis=axes, keepdims=True)
        var = jnp.var(x32, axis=axes, keepdims=True)
        out = ((x32 - mean) * jax.lax.rsqrt(var + epsilon)).reshape(n, c, *spatial)
        shape = [1, c] + [1] * len(spatial)
        i = 0
        if has_w:
            out = out * rest[i].reshape(shape).astype(jnp.float32)
            i += 1
        if has_b:
            out = out + rest[i].reshape(shape).astype(jnp.float32)
        out = out.astype(a.dtype)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    return run_op("group_norm", fn, ins)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        sq = jnp.square(a)
        half = size // 2
        c = a.shape[1]
        pads = [(0, 0)] * a.ndim
        pads[1] = (half, size - half - 1)
        sq = jnp.pad(sq, pads)
        window = [1] * a.ndim
        window[1] = size
        summed = jax.lax.reduce_window(sq, 0.0, jax.lax.add, window, [1] * a.ndim, "VALID")
        return a / jnp.power(k + alpha * summed, beta)

    return run_op("local_response_norm", fn, [_t(x)])
