"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "cross_entropy",
    "softmax_with_cross_entropy",
    "mse_loss",
    "l1_loss",
    "nll_loss",
    "binary_cross_entropy",
    "binary_cross_entropy_with_logits",
    "smooth_l1_loss",
    "kl_div",
    "margin_ranking_loss",
    "cosine_embedding_loss",
    "triplet_margin_loss",
    "hinge_embedding_loss",
    "square_error_cost",
    "log_loss",
    "ctc_loss",
    "sigmoid_focal_loss",
    "hsigmoid_loss",
    "margin_cross_entropy",
    "class_center_sample",
]


def class_center_sample(label, num_classes, num_samples, group=None):
    """PartialFC class-center sampling (reference nn/functional/common.py
    class_center_sample; kernel class_center_sample_kernel.cu). Keeps all
    positive centers, pads with uniformly sampled negatives, remaps labels
    into the sampled index space. Host-side: the sampled set size is
    data-dependent.

    Model-parallel (`group` a comm group): each rank samples within its
    own class shard; positives are shared via an object all-gather so every
    rank remaps consistently (reference's NCCLAllGather of positives)."""
    mp = group is not None and group is not False

    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    lab = lab.reshape(-1).astype(np.int64)
    if mp and not isinstance(group, bool):
        from ...distributed import collective as dist
        from ...distributed.env import get_rank

        all_lab = lab
        if group.nranks > 1:
            gathered = []
            dist.all_gather_object(gathered, lab.tolist(), group)
            all_lab = np.asarray(sorted(
                {v for part in gathered for v in part}), np.int64)
        nranks = group.nranks
        rank = group.ranks.index(get_rank())
    else:
        all_lab = lab
        nranks, rank = 1, 0
    per = num_classes  # classes on THIS rank's shard
    offset = rank * per if nranks > 1 else 0
    in_shard = (all_lab >= offset) & (all_lab < offset + per)
    pos = np.unique(all_lab[in_shard] - offset)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(per), pos, assume_unique=True)
        extra = np.random.default_rng().choice(
            neg_pool, size=num_samples - len(pos), replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = np.full(per, -1, np.int64)
    remap[sampled] = np.arange(len(sampled))
    own = (lab >= offset) & (lab < offset + per)
    new_label = np.where(own, remap[np.clip(lab - offset, 0, per - 1)],
                         lab)
    return (to_tensor(new_label.astype(np.int64)),
            to_tensor(sampled.astype(np.int64)))


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def _sparse_ce_impl(logits, safe_ids):
    """Shared primal math for _sparse_ce and its VJP fwd: (loss, lse)."""
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    tgt = jnp.take_along_axis(lf, safe_ids[..., None], axis=-1)[..., 0]
    return lse - tgt, lse


@jax.custom_vjp
def _sparse_ce(logits, safe_ids):
    """Memory-lean sparse softmax-CE: lse - target_logit per row.

    The straight `log_softmax + gather` formulation makes AD save the full
    f32 log-probs tensor as a residual — 3.3GB for the GPT-3 125M bench
    shape [8, 2048, 50k], a pure HBM tax (round-5 breakdown: the lm-head+CE
    block ran at half the step's efficiency). This custom VJP saves only
    (logits, lse) and reconstructs softmax in the backward. Reference
    analog: c_softmax_with_cross_entropy / fused CE kernels."""
    return _sparse_ce_impl(logits, safe_ids)[0]


def _sparse_ce_fwd(logits, safe_ids):
    loss, lse = _sparse_ce_impl(logits, safe_ids)
    return loss, (logits, safe_ids, lse)


def _sparse_ce_bwd(res, g):
    logits, safe_ids, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse[..., None])  # softmax, recomputed not stored
    onehot = (jax.lax.broadcasted_iota(jnp.int32, lf.shape, lf.ndim - 1)
              == safe_ids[..., None])
    dl = (p - onehot.astype(jnp.float32)) * g[..., None]
    return (dl.astype(logits.dtype),
            np.zeros(safe_ids.shape, jax.dtypes.float0))


_sparse_ce.defvjp(_sparse_ce_fwd, _sparse_ce_bwd)


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    """reference: python/paddle/nn/functional/loss.py cross_entropy — the
    sparse path computes log-softmax + one gather; on TPU this fuses into the
    final projection matmul (the reference needs ParallelCrossEntropy-style
    fused kernels for the same effect)."""
    ins = [_t(input), _t(label)]
    has_w = weight is not None
    if has_w:
        ins.append(_t(weight))

    def fn(logits, lab, *rest):
        def _logp():
            if use_softmax:
                return jax.nn.log_softmax(logits.astype(jnp.float32),
                                          axis=axis)
            return jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30, None))

        if soft_label:
            logp = _logp()
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            if has_w:
                w = rest[0].astype(jnp.float32)
                loss = loss * jnp.sum(tgt * w, axis=axis)
            return _reduce(loss, reduction)
        ids = lab.astype(jnp.int32)
        squeeze_last = ids.ndim == logits.ndim and ids.shape[axis] == 1
        if squeeze_last:
            ids = jnp.squeeze(ids, axis)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        if (use_softmax and not has_w and label_smoothing == 0
                and axis in (-1, logits.ndim - 1)):
            # hot path (LLM pretraining loss): custom-VJP CE that never
            # materializes the f32 log-probs tensor (see _sparse_ce)
            loss = jnp.where(valid, _sparse_ce(logits, safe), 0.0)
            if reduction == "mean":
                n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)),
                                      1.0)
                return jnp.sum(loss) / n_valid
            return _reduce(loss, reduction)
        logp = _logp()
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, axis), axis=axis
        ).squeeze(axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            smooth = jnp.mean(logp, axis=axis)
            loss = -((1 - label_smoothing) * picked + label_smoothing * smooth)
        else:
            loss = -picked
        if has_w:
            w = rest[0].astype(jnp.float32)
            sample_w = jnp.take(w, safe) * valid.astype(jnp.float32)
            loss = loss * sample_w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(sample_w), 1e-12)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / n_valid
        return _reduce(loss, reduction)

    return run_op("cross_entropy", fn, ins)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from .activation import softmax as _softmax

    loss = run_op("unsqueeze_loss", lambda a: jnp.expand_dims(a, axis), [loss])
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return run_op(
        "mse_loss",
        lambda a, b: _reduce(jnp.square(a - b), reduction),
        [_t(input), _t(label)],
    )


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return run_op(
        "l1_loss",
        lambda a, b: _reduce(jnp.abs(a - b), reduction),
        [_t(input), _t(label)],
    )


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    ins = [_t(input), _t(label)]
    has_w = weight is not None
    if has_w:
        ins.append(_t(weight))

    def fn(logp, lab, *rest):
        ids = lab.astype(jnp.int32)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        loss = -picked
        w = rest[0] if has_w else None
        if w is not None:
            sw = jnp.take(w, safe) * valid.astype(logp.dtype)
            loss = loss * sw
            if reduction == "mean":
                return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.maximum(jnp.sum(sw), 1e-12)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce(loss, reduction)

    return run_op("nll_loss", fn, ins)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    ins = [_t(input), _t(label)]
    has_w = weight is not None
    if has_w:
        ins.append(_t(weight))

    def fn(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if has_w:
            loss = loss * rest[0]
        return _reduce(loss, reduction)

    return run_op("bce", fn, ins)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    ins = [_t(logit), _t(label)]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        ins.append(_t(weight))
    if has_pw:
        ins.append(_t(pos_weight))

    def fn(z, y, *rest):
        i = 0
        w = None
        pw = None
        if has_w:
            w = rest[i]
            i += 1
        if has_pw:
            pw = rest[i]
        # numerically stable: max(z,0) - z*y + log(1+exp(-|z|)), with pos_weight
        log_sig_pos = -jax.nn.softplus(-z)
        log_sig_neg = -z - jax.nn.softplus(-z)
        if pw is not None:
            loss = -(pw * y * log_sig_pos + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig_pos + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    return run_op("bce_with_logits", fn, ins)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)

    return run_op("smooth_l1", fn, [_t(input), _t(label)])


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def fn(logp, tgt):
        if log_target:
            loss = jnp.exp(tgt) * (tgt - logp)
        else:
            safe_t = jnp.clip(tgt, 1e-12, None)
            loss = tgt * (jnp.log(safe_t) - logp)
            loss = jnp.where(tgt > 0, loss, 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return run_op("kl_div", fn, [_t(input), _t(label)])


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    return run_op(
        "margin_ranking",
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        [_t(input), _t(other), _t(label)],
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / (
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1) + 1e-12
        )
        loss = jnp.where(y > 0, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return run_op("cosine_embedding", fn, [_t(input1), _t(input2), _t(label)])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):  # noqa: A002
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return run_op("triplet_margin", fn, [_t(input), _t(positive), _t(negative)])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    return run_op(
        "hinge_embedding",
        lambda a, y: _reduce(
            jnp.where(y > 0, a, jnp.maximum(0.0, margin - a)), reduction
        ),
        [_t(input), _t(label)],
    )


def square_error_cost(input, label):  # noqa: A002
    return run_op("square_error_cost", lambda a, b: jnp.square(a - b), [_t(input), _t(label)])


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    return run_op(
        "log_loss",
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        [_t(input), _t(label)],
    )


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over time).
    reference: warpctc-backed ctc_loss. log_probs: [T, B, C]."""
    ins = [_t(log_probs), _t(labels), _t(input_lengths), _t(label_lengths)]

    def fn(lp, lab, ilen, llen):
        T, B, C = lp.shape
        lab = lab.astype(jnp.int32)
        S = lab.shape[1]
        # extended label sequence with blanks: length 2S+1
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        ext_len = 2 * llen.astype(jnp.int32) + 1

        neg_inf = -1e30
        alpha0 = jnp.full((B, 2 * S + 1), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, :, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lab)

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1
        )

        def step(alpha, lp_t):
            a_shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, t):
            alpha = carry
            new_alpha, _ = step(alpha, lp[t])
            alpha = jnp.where((t < ilen.astype(jnp.int32))[:, None], new_alpha, alpha)
            return alpha, None

        alpha, _ = jax.lax.scan(scan_body, alpha0, jnp.arange(1, T))
        idx_last = ext_len - 1
        idx_prev = jnp.maximum(ext_len - 2, 0)
        ll = jnp.logaddexp(
            jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0],
            jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0],
        )
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(llen.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return run_op("ctc_loss", fn, ins)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    ins = [_t(logit), _t(label)]
    has_n = normalizer is not None
    if has_n:
        ins.append(_t(normalizer))

    def fn(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jax.nn.softplus(-z) * y + jax.nn.softplus(z) * (1 - y)
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if has_n:
            loss = loss / rest[0]
        return _reduce(loss, reduction)

    return run_op("sigmoid_focal_loss", fn, ins)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference nn/functional/loss.py
    hsigmoid_loss; kernel phi/kernels/cpu/hsigmoid_loss_kernel.cc).

    Default tree: complete binary tree with heap indexing — leaf for class
    l sits at heap position l + num_classes; the root-to-leaf path visits
    internal nodes (l+C)>>1, (l+C)>>2, ..., 1 and the step's code bit is
    the corresponding bit of l+C. Internal node n uses weight row n-1.
    Custom trees come in via path_table/path_code (both [N, L], -1 padded).
    One gather + one matmul per batch — no per-node loop."""
    import math

    C = int(num_classes)
    use_custom = path_table is not None

    if use_custom:
        def fn(x, lab, pt, pc, w, *rest):
            b = rest[0] if rest else None
            mask = (pt >= 0).astype(x.dtype)
            rows = jnp.clip(pt, 0, w.shape[0] - 1).astype(jnp.int32)
            wv = w[rows]                       # [N, L, D]
            logit = jnp.einsum("nd,nld->nl", x, wv)
            if b is not None:
                logit = logit + b[rows].reshape(logit.shape)
            code = pc.astype(x.dtype)
            # BCE with logit: softplus(logit) - code*logit
            per = (jax.nn.softplus(logit) - code * logit) * mask
            return per.sum(-1, keepdims=True)

        ins = [input, label, path_table, path_code, weight]
        if bias is not None:
            ins.append(bias)
        return run_op("hsigmoid_loss", fn, ins)

    depth = max(int(math.ceil(math.log2(max(C, 2)))), 1)

    def fn(x, lab, w, *rest):
        b = rest[0] if rest else None
        heap = lab.astype(jnp.int32) + C        # [N]
        ks = jnp.arange(depth, 0, -1)           # depth..1
        anc = (heap[:, None] >> ks[None, :])    # ancestors root..parent
        valid = (anc >= 1).astype(x.dtype)
        code = ((heap[:, None] >> (ks[None, :] - 1)) & 1).astype(x.dtype)
        rows = jnp.clip(anc - 1, 0, w.shape[0] - 1)
        wv = w[rows]                            # [N, L, D]
        logit = jnp.einsum("nd,nld->nl", x, wv)
        if b is not None:
            logit = logit + b[rows].reshape(logit.shape)
        per = (jax.nn.softplus(logit) - code * logit) * valid
        return per.sum(-1, keepdims=True)

    ins = [input, label, weight]
    if bias is not None:
        ins.append(bias)
    return run_op("hsigmoid_loss", fn, ins)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax CE (reference nn/functional/loss.py
    margin_cross_entropy; kernel margin_cross_entropy_kernel.cu). The true
    class logit cosθ becomes cos(m1·θ + m2) − m3, everything scales by s.

    Model-parallel: when `group` is a communication group, `logits` is this
    rank's class shard [N, C/world]; the softmax statistics (row max, exp
    sum) and the target logit reduce over the group — the same three
    collectives the reference's MP kernel issues.
    """
    from ...distributed import collective as dist
    from ...distributed.env import get_rank

    mp = group is not None and group is not False
    if mp:
        g = group if not isinstance(group, bool) else None
        nranks = g.nranks if g is not None else 1
        rank = (g.ranks.index(get_rank()) if g is not None else 0)
    else:
        nranks, rank = 1, 0
    C_local = int(logits.shape[1])
    offset = rank * C_local

    def fn(lg, lab):
        lab = lab.reshape(-1).astype(jnp.int32)
        local = (lab >= offset) & (lab < offset + C_local)
        idx = jnp.clip(lab - offset, 0, C_local - 1)
        rows = jnp.arange(lg.shape[0])
        target = lg[rows, idx]
        # margins on the cosine of the true class
        theta = jnp.arccos(jnp.clip(target, -1.0, 1.0))
        modified = jnp.cos(margin1 * theta + margin2) - margin3
        lg2 = lg.at[rows, idx].set(
            jnp.where(local, modified, target))
        return lg2 * scale

    scaled = run_op("margin_logits", fn, [logits, label])

    if nranks > 1:
        # global softmax over the sharded class dim
        mx = scaled.max(axis=1, keepdim=True)
        dist.all_reduce(mx, op=dist.ReduceOp.MAX, group=group)
        e = (scaled - mx).exp()
        ssum = e.sum(axis=1, keepdim=True)
        dist.all_reduce(ssum, group=group)
        softmax = e / ssum

        def tgt(lg, lab):
            lab = lab.reshape(-1).astype(jnp.int32)
            local = (lab >= offset) & (lab < offset + C_local)
            idx = jnp.clip(lab - offset, 0, C_local - 1)
            t = lg[jnp.arange(lg.shape[0]), idx]
            return jnp.where(local, t, 0.0)

        tlogit = run_op("margin_target", tgt, [scaled, label])
        dist.all_reduce(tlogit, group=group)
        mxv = run_op("margin_sq", lambda m: m.reshape(-1), [mx])
        lsum = run_op("margin_lse", lambda s: jnp.log(s).reshape(-1),
                      [ssum])
        loss = lsum + mxv - tlogit
        loss = loss.reshape([-1, 1])
    else:
        def lfn(lg, lab):
            lab = lab.reshape(-1).astype(jnp.int32)
            lse = jax.nn.logsumexp(lg, axis=1)
            t = lg[jnp.arange(lg.shape[0]), lab]
            return (lse - t).reshape(-1, 1)

        loss = run_op("margin_ce", lfn, [scaled, label])
        softmax = run_op("margin_softmax",
                         lambda lg: jax.nn.softmax(lg, axis=1), [scaled])

    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    elif reduction is not None:
        raise ValueError(f"unknown reduction {reduction!r}")
    if return_softmax:
        return loss, softmax
    return loss
