"""Convolution functionals (reference: python/paddle/nn/functional/conv.py).

All lower to lax.conv_general_dilated, which XLA maps onto the MXU —
the entire phi conv kernel zoo (gpudnn, cutlass conv2d fusions) collapses
into this one primitive plus XLA epilogue fusion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "conv1d",
    "conv2d",
    "conv3d",
    "conv1d_transpose",
    "conv2d_transpose",
    "conv3d_transpose",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _padding(padding, n):
    """Paddle padding spec -> lax padding list or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[pt,pb],[pl,pr]] including batch/channel
    if len(padding) == n + 2:
        return [(int(p[0]), int(p[1])) for p in padding[2:]]
    raise ValueError(f"unsupported padding spec {padding!r}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channels_last = not data_format.startswith("NC")
    if channels_last:
        spec_map = {1: ("NWC", "OIW", "NWC"), 2: ("NHWC", "OIHW", "NHWC"), 3: ("NDHWC", "OIDHW", "NDHWC")}
    else:
        spec_map = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"), 3: ("NCDHW", "OIDHW", "NCDHW")}
    dn = spec_map[n]
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _padding(padding, n)
    ins = [_t(x), _t(weight)]
    has_bias = bias is not None
    if has_bias:
        ins.append(_t(bias))

    def fn(a, w, *rest):
        # No preferred_element_type: the MXU already accumulates bf16 convs in
        # f32 natively, and requesting an f32 output breaks JAX's conv
        # transpose rule under AMP O2 (bf16 lhs vs f32 cotangent ->
        # "requires arguments to have the same dtypes"). Reference AMP white
        # list keeps conv in low precision (python/paddle/amp/amp_lists.py).
        # float16 has no native MXU path and only ~3 exponent headroom bits,
        # so its convs run through an f32 upcast (differentiable, keeps f32
        # accumulation) rather than preferred_element_type.
        a_c, w_c = (a, w) if a.dtype != jnp.float16 else (
            a.astype(jnp.float32), w.astype(jnp.float32))
        out = jax.lax.conv_general_dilated(
            a_c,
            w_c,
            window_strides=strides,
            padding=pad,
            rhs_dilation=dil,
            dimension_numbers=dn,
            feature_group_count=int(groups),
        ).astype(a.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if not channels_last else -1] = b.size
            out = out + b.reshape(shape)
        return out

    return run_op(f"conv{n}d", fn, ins)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n, data_format, output_size):
    channels_last = not data_format.startswith("NC")
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _padding(padding, n)
    opad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    ins = [_t(x), _t(weight)]
    has_bias = bias is not None
    if has_bias:
        ins.append(_t(bias))

    def fn(a, w, *rest):
        # weight layout is [in_c, out_c/groups, *k] (paddle transpose-conv
        # convention); use gradient-based transpose conv:
        # conv_transpose = lhs-dilated conv with flipped kernel
        out_dtype = a.dtype
        if a.dtype == jnp.float16:  # f32 accumulation (see _conv above)
            a, w = a.astype(jnp.float32), w.astype(jnp.float32)
        if channels_last:
            a_ncx = jnp.moveaxis(a, -1, 1)
        else:
            a_ncx = a
        in_c = a_ncx.shape[1]
        kdims = w.shape[2:]
        if isinstance(pad, str):
            if pad == "SAME":
                pads = [((k - 1) // 2, (k - 1) // 2) for k in kdims]
            else:
                pads = [(0, 0)] * n
        else:
            pads = pad
        if groups > 1:
            # regroup: full weight [in_c, out_c/g, *k] with groups along in_c
            wg = w.reshape((groups, in_c // groups) + w.shape[1:])
            outs = []
            for g in range(groups):
                wgf = jnp.flip(wg[g], axis=tuple(range(2, 2 + n)))
                wgf = jnp.swapaxes(wgf, 0, 1)
                outs.append(_transpose_one(a_ncx[:, g * (in_c // groups):(g + 1) * (in_c // groups)], wgf, strides, pads, dil, opad, n))
            out = jnp.concatenate(outs, axis=1)
        else:
            # flip spatial dims, swap io: [in, out, *k] -> [out, in, *k]
            wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
            wf = jnp.swapaxes(wf, 0, 1)
            out = _transpose_one(a_ncx, wf, strides, pads, dil, opad, n)
        if rest:
            b = rest[0]
            out = out + b.reshape((1, b.size) + (1,) * n)
        if channels_last:
            out = jnp.moveaxis(out, 1, -1)
        return out.astype(out_dtype)

    return run_op(f"conv{n}d_transpose", fn, ins)


def _transpose_one(a, wf, strides, pads, dil, opad, n):
    spec = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"), 3: ("NCDHW", "OIDHW", "NCDHW")}[n]
    kdims = wf.shape[2:]
    tpads = []
    for k, s, (plo, phi), d, op in zip(kdims, strides, pads, dil, opad):
        keff = d * (k - 1) + 1
        tpads.append((keff - 1 - plo, keff - 1 - phi + op))
    return jax.lax.conv_general_dilated(
        a,
        wf,
        window_strides=(1,) * n,
        padding=tpads,
        lhs_dilation=strides,
        rhs_dilation=dil,
        dimension_numbers=spec,
    )


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format, output_size)
