"""Attention functionals (reference: python/paddle/nn/functional/flash_attention.py:
flash_attention :358, scaled_dot_product_attention :1139, flashmask_attention :1299).

Paddle layout: q/k/v are [batch, seq, num_heads, head_dim].

Dispatch: on TPU these route to the Pallas flash-attention kernel
(paddle_tpu.ops.pallas.flash_attention) — the analog of the reference's
dynloaded flashattn library (paddle/phi/kernels/gpu/flash_attn_kernel.cu);
elsewhere (CPU tests) they fall back to the jnp reference implementation.
GQA/MQA (fewer kv heads) is supported by head repetition in the reference
path and natively in the kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "scaled_dot_product_attention",
    "flash_attention",
    "flash_attn_unpadded",
    "flashmask_attention",
    "sdp_kernel",
]

_USE_PALLAS = True


def _use_pallas_kernel():
    if not _USE_PALLAS:
        return False
    from ...ops.pallas import interpret_mode

    if interpret_mode():
        return True
    try:
        import jax

        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _ref_attention(q, k, v, mask=None, causal=False, scale=None, dropout=0.0, dropout_key=None):
    """jnp reference: q/k/v [B, S, H, D] -> [B, S, H, D]; f32 softmax."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    Hkv = k.shape[2]
    if Hkv != H:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / (D ** 0.5)
    # [B,H,Sq,Skv]
    logits = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32) * s
    if causal:
        cm = jnp.tril(jnp.ones((Sq, Skv), bool), k=Skv - Sq)
        logits = jnp.where(cm[None, None], logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    p = jax.nn.softmax(logits, axis=-1)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    ins = [_t(query), _t(key), _t(value)]
    has_mask = attn_mask is not None
    if has_mask:
        ins.append(_t(attn_mask))
    dkey = None
    if dropout_p > 0.0 and training:
        from ...framework import random as rnd

        dkey = rnd.next_key()

    # a [B,1,1,Skv]-broadcastable mask is a per-KEY padding mask — the
    # encoder-model case (BERT/ERNIE) — and rides the Pallas kernel as a
    # fused additive key bias instead of forcing the S^2-materializing
    # composite (round-5: this was BERT's bottleneck)
    key_padding = False
    if has_mask:
        mshape = tuple(ins[3].shape)
        key_padding = (len(mshape) == 4 and mshape[1] == 1 and mshape[2] == 1
                       and mshape[3] == ins[1].shape[1]
                       and mshape[0] in (1, ins[0].shape[0])
                       # a LEARNED bias needs its gradient, which the
                       # kernel's key-bias path does not produce — keep the
                       # exact composite for trainable masks
                       and getattr(ins[3], "stop_gradient", True))

    if (_use_pallas_kernel() and dropout_p == 0.0
            and (not has_mask or key_padding)):
        from ...ops.pallas.flash_attention import flash_attention_fwd

        def fnp(q, k, v, *rest):
            kb = None
            if rest:
                m = rest[0].reshape(rest[0].shape[0], -1)
                if m.dtype == jnp.bool_:
                    kb = jnp.where(m, 0.0, -1e30).astype(jnp.float32)
                else:
                    kb = m.astype(jnp.float32)
                if kb.shape[0] == 1 and q.shape[0] > 1:
                    kb = jnp.broadcast_to(kb, (q.shape[0], kb.shape[1]))
            return flash_attention_fwd(q, k, v, causal=is_causal,
                                       key_bias=kb)

        return run_op("flash_attention", fnp, ins)

    def fn(q, k, v, *rest):
        mask = rest[0] if has_mask else None
        return _ref_attention(
            q, k, v, mask=mask, causal=is_causal,
            dropout=dropout_p if training else 0.0, dropout_key=dkey,
        )

    return run_op("sdpa", fn, ins)


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """reference: flash_attention (flash_attention.py:358). Returns
    (out, softmax_lse_placeholder) tuple like the reference API."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(
    query,
    key,
    value,
    cu_seqlens_q,
    cu_seqlens_k,
    max_seqlen_q,
    max_seqlen_k,
    scale,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """Varlen attention over packed sequences (reference: flash_attn_unpadded).
    q/k/v: [total_tokens, H, D]; cu_seqlens: [B+1] prefix sums. Implemented by
    segment ids over the packed layout — the TPU-idiomatic ragged encoding;
    on TPU it runs the Pallas varlen kernel (block-sparse: tiles whose q/k
    segments cannot intersect are skipped), elsewhere a dense jnp fallback."""
    ins = [_t(query), _t(key), _t(value), _t(cu_seqlens_q), _t(cu_seqlens_k)]

    if _use_pallas_kernel() and dropout == 0.0:
        from ...ops.pallas.masked_flash import varlen_flash_attention_fwd

        def fnp(q, k, v, cq, ck):
            return varlen_flash_attention_fwd(q, k, v, cq, ck, scale,
                                              causal=causal)

        out = run_op("flash_attn_unpadded", fnp, ins)
        return out, None

    def fn(q, k, v, cq, ck):
        Tq, H, D = q.shape
        Tk = k.shape[0]
        seg_q = jnp.cumsum(
            jnp.zeros(Tq, jnp.int32).at[cq.astype(jnp.int32)[1:-1]].add(1)
        )
        seg_k = jnp.cumsum(
            jnp.zeros(Tk, jnp.int32).at[ck.astype(jnp.int32)[1:-1]].add(1)
        )
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(Tq) - jnp.take(cq.astype(jnp.int32), seg_q)
            pos_k = jnp.arange(Tk) - jnp.take(ck.astype(jnp.int32), seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask[None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v)
        return out.astype(q.dtype)

    out = run_op("flash_attn_unpadded", fn, ins)
    return out, None


def flashmask_attention(
    query,
    key,
    value,
    startend_row_indices=None,
    dropout=0.0,
    causal=False,
    window_size=None,
    return_softmax_lse=False,
    return_seed_offset=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """Sparse block-mask attention (reference: flashmask_attention,
    flash_attention.py:1299). startend_row_indices [B, Hm, Sk, 1|2|4] encodes,
    per key column, the query-row range that is MASKED OUT:
    - causal + last-dim 1: rows >= start masked (below the band)
    - causal + last-dim 2: [start, end) masked
    - non-causal + 2: (LTS, UTE) — rows >= LTS or < UTE masked
    - non-causal + 4: (LTS, LTE, UTS, UTE) — [LTS,LTE) and [UTS,UTE) masked
    """
    ins = [_t(query), _t(key), _t(value)]
    has_idx = startend_row_indices is not None
    if has_idx:
        ins.append(_t(startend_row_indices))

    if (_use_pallas_kernel() and has_idx and dropout == 0.0
            and window_size is None and not return_softmax_lse):
        from ...ops.pallas.masked_flash import flashmask_attention_fwd

        def fnp(q, k, v, idx):
            return flashmask_attention_fwd(q, k, v, idx, causal=causal)

        out = run_op("flashmask_attention", fnp, ins)
        if return_seed_offset:
            return (out, *([None] * int(return_seed_offset)))
        return out

    def fn(q, k, v, *rest):
        B, Sq, H, D = q.shape
        Sk = k.shape[1]
        if k.shape[2] != H:  # GQA/MQA: repeat kv heads
            rep_kv = H // k.shape[2]
            k = jnp.repeat(k, rep_kv, axis=2)
            v = jnp.repeat(v, rep_kv, axis=2)
        rows = jnp.arange(Sq)[:, None]  # query row
        mask_keep = jnp.ones((B, 1, Sq, Sk), bool)
        if has_idx:
            idx = rest[0].astype(jnp.int32)  # [B, Hm, Sk, n]
            n = idx.shape[-1]
            idxb = jnp.moveaxis(idx, 2, -1)  # [B, Hm, n, Sk]
            if causal:
                if n == 1:
                    start = idxb[:, :, 0][:, :, None, :]  # [B,Hm,1,Sk]
                    masked = rows[None, None] >= start
                else:
                    start = idxb[:, :, 0][:, :, None, :]
                    end = idxb[:, :, 1][:, :, None, :]
                    masked = (rows[None, None] >= start) & (rows[None, None] < end)
            else:
                if n == 2:
                    lts = idxb[:, :, 0][:, :, None, :]
                    ute = idxb[:, :, 1][:, :, None, :]
                    masked = (rows[None, None] >= lts) | (rows[None, None] < ute)
                else:
                    lts = idxb[:, :, 0][:, :, None, :]
                    lte = idxb[:, :, 1][:, :, None, :]
                    uts = idxb[:, :, 2][:, :, None, :]
                    ute = idxb[:, :, 3][:, :, None, :]
                    masked = ((rows[None, None] >= lts) & (rows[None, None] < lte)) | (
                        (rows[None, None] >= uts) & (rows[None, None] < ute)
                    )
            mask_keep = ~masked  # [B, Hm, Sq, Sk]
        if causal:
            cm = jnp.tril(jnp.ones((Sq, Sk), bool), k=Sk - Sq)
            mask_keep = mask_keep & cm[None, None]
        Hm = mask_keep.shape[1]
        scale = 1.0 / (D ** 0.5)
        logits = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32) * scale
        if Hm == 1:
            m = mask_keep
        else:
            rep = H // Hm
            m = jnp.repeat(mask_keep, rep, axis=1)
        logits = jnp.where(m, logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhst,bthd->bshd", p.astype(v.dtype), v)
        return out.astype(q.dtype)

    out = run_op("flashmask_attention", fn, ins)
    if return_softmax_lse or return_seed_offset:
        extra = [None] * (int(return_softmax_lse) + int(return_seed_offset))
        return (out, *extra)
    return out


class sdp_kernel:
    """Context manager selecting attention backends (API parity with the
    reference's sdp_kernel; on TPU the Pallas kernel is always preferred)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        self.enable_flash = enable_flash

    def __enter__(self):
        global _USE_PALLAS
        self._saved = _USE_PALLAS
        _USE_PALLAS = self.enable_flash
        return self

    def __exit__(self, *exc):
        global _USE_PALLAS
        _USE_PALLAS = self._saved
        return False


def ring_flash_attention(query, key, value, causal=True, axis="sep", name=None):
    """Context-parallel exact attention: sequence sharded over the `sep` mesh
    axis, K/V blocks rotating on the ICI ring with online-softmax accumulation
    (paddle_tpu.parallel.ring). The reference snapshot has no ring attention
    (SURVEY §5.7) — this is the TPU-native long-context upgrade over its bare
    SEP-axis plumbing (fleet/meta_parallel/segment_parallel.py:26).

    Falls back to dense reference attention when no mesh is active or the
    axis degree is 1, so models are portable across parallel configs.
    """
    from ...distributed import env as _env
    from ...parallel.ring import ring_attention_spmd

    mesh = _env.get_global_mesh()
    use_ring = mesh is not None and mesh.shape.get(axis, 1) > 1

    def fn(q, k, v):
        if use_ring:
            return ring_attention_spmd(q, k, v, mesh, axis=axis, causal=causal)
        return _ref_attention(q, k, v, causal=causal)

    return run_op("ring_flash_attention", fn, [_t(query), _t(key), _t(value)])


__all__.append("ring_flash_attention")
