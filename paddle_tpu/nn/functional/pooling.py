"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).
All lower to lax.reduce_window."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "avg_pool1d",
    "avg_pool2d",
    "avg_pool3d",
    "max_pool1d",
    "max_pool2d",
    "max_pool3d",
    "adaptive_avg_pool1d",
    "adaptive_avg_pool2d",
    "adaptive_avg_pool3d",
    "adaptive_max_pool1d",
    "adaptive_max_pool2d",
    "adaptive_max_pool3d",
    "max_unpool1d",
    "max_unpool2d",
    "max_unpool3d",
    "lp_pool1d",
    "lp_pool2d",
    "fractional_max_pool2d",
    "fractional_max_pool3d",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding!r}")


def _pool(x, kernel, stride, padding, n, reducer, init, ceil_mode, count_include_pad, data_format, is_avg):
    channels_last = not data_format.startswith("NC")
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)

    def fn(a):
        nd = a.ndim
        if channels_last:
            spatial = list(range(1, nd - 1))
        else:
            spatial = list(range(2, nd))
        window = [1] * nd
        strides = [1] * nd
        for i, ax in enumerate(spatial):
            window[ax] = k[i]
            strides[ax] = s[i]
        if isinstance(pads, str):
            padcfg = pads
        else:
            padcfg = [(0, 0)] * nd
            for i, ax in enumerate(spatial):
                padcfg[ax] = pads[i]
        if is_avg:
            ones = jnp.ones_like(a)
            summed = jax.lax.reduce_window(a, 0.0 if a.dtype != jnp.bfloat16 else jnp.bfloat16(0), jax.lax.add, window, strides, padcfg)
            if count_include_pad:
                denom = float(np.prod(k))
                return (summed / denom).astype(a.dtype)
            counts = jax.lax.reduce_window(ones, 0.0 if a.dtype != jnp.bfloat16 else jnp.bfloat16(0), jax.lax.add, window, strides, padcfg)
            return (summed / counts).astype(a.dtype)
        return jax.lax.reduce_window(a, init(a.dtype), reducer, window, strides, padcfg)

    return run_op("pool", fn, [_t(x)])


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add, None, ceil_mode, not exclusive, "NCL", True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add, None, ceil_mode, not exclusive, data_format, True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add, None, ceil_mode, not exclusive, data_format, True)


def _neg_inf(dtype):
    # must be a Python scalar literal: reduce_window's autodiff rule only
    # recognizes the max-monoid when init_value is the -inf constant
    return -np.inf if jnp.issubdtype(dtype, jnp.floating) else int(jnp.iinfo(dtype).min)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        if ceil_mode:
            raise NotImplementedError(
                "max_pool1d: return_mask with ceil_mode is not supported")
        return _max_pool_with_index(x, kernel_size, stride, padding, 1)
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, _neg_inf, ceil_mode, False, "NCL", False)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if ceil_mode or data_format != "NCHW":
            raise NotImplementedError(
                "max_pool2d: return_mask requires NCHW and ceil_mode=False")
        return _max_pool_with_index(x, kernel_size, stride, padding, 2)
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.max, _neg_inf, ceil_mode, False, data_format, False)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if ceil_mode or data_format != "NCDHW":
            raise NotImplementedError(
                "max_pool3d: return_mask requires NCDHW and ceil_mode=False")
        return _max_pool_with_index(x, kernel_size, stride, padding, 3)
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, _neg_inf, ceil_mode, False, data_format, False)


def _adaptive(x, output_size, n, is_avg, data_format="NCHW"):
    xx = _t(x)
    out_sizes = _tuple(output_size, n)

    def fn(a):
        nd = a.ndim
        spatial = list(range(2, nd))
        out = a
        for i, ax in enumerate(spatial):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            if isz % osz == 0:
                k = isz // osz
                shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1:]
                r = out.reshape(shape)
                out = jnp.mean(r, axis=ax + 1) if is_avg else jnp.max(r, axis=ax + 1)
            else:
                # general adaptive pooling: per-output-bin segments
                starts = (np.arange(osz) * isz) // osz
                ends = -(-((np.arange(osz) + 1) * isz) // osz)
                slices = []
                for st, en in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return run_op("adaptive_pool", fn, [xx])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, False)


# --------------------------------------------------------------------------- #
# pooling tail: argmax masks, unpool, lp / fractional pools
# (reference: python/paddle/nn/functional/pooling.py max_pool2d return_mask,
#  max_unpool1d/2d/3d, lp_pool1d/2d; kernels phi/kernels/gpu/pool_kernel.cu,
#  unpool_kernel.cu — here patch-extraction + argmax/scatter, which XLA
#  lowers to one fused gather/scatter program)
# --------------------------------------------------------------------------- #

def _max_pool_with_index(x, kernel_size, stride, padding, n):
    """Returns (pooled, mask) where mask holds flat indices into the input
    spatial volume (paddle convention for max_pool*d(return_mask=True))."""
    xx = _t(x)
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    p = _pads(padding, n)

    def fn(a):
        spatial = a.shape[2:]
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=k, window_strides=s, padding=list(p))
        B, _CK, *out_sp = patches.shape
        C = a.shape[1]
        kk = int(np.prod(k))
        # patches channel order is [C, *kernel] flattened C-major
        pv = patches.reshape(B, C, kk, *out_sp)
        # patches pads with ZEROS; mask padded taps to -inf so both the max
        # value and the argmax match -inf-padded pooling semantics.
        # tap (local kernel coords) -> global coord per dim:
        tap = jnp.arange(kk)
        tap_coords = []
        rem = tap
        for d in range(n - 1, -1, -1):
            tap_coords.append(rem % k[d])
            rem = rem // k[d]
        tap_coords = tap_coords[::-1]  # per-dim [kk]
        valid = None
        glob = []
        for d in range(n):
            o = jnp.arange(out_sp[d]) * s[d] - p[d][0]
            shape_t = [1, 1, kk] + [1] * n
            shape_o = [1, 1, 1] + [1] * n
            shape_o[3 + d] = out_sp[d]
            g = (tap_coords[d].reshape(shape_t)
                 + o.reshape(shape_o))  # [1,1,kk,...,out_d,...]
            glob.append(g)
            ok = (g >= 0) & (g < spatial[d])
            valid = ok if valid is None else (valid & ok)
        neg = jnp.asarray(jnp.finfo(a.dtype).min, a.dtype)
        pv = jnp.where(valid, pv, neg)
        idx_local = jnp.argmax(pv, axis=2)  # [B, C, *out_sp]
        val = jnp.max(pv, axis=2)
        flat = jnp.zeros_like(idx_local)
        for d in range(n):
            g_at = jnp.take_along_axis(
                jnp.broadcast_to(glob[d], (1, 1, kk) + tuple(out_sp)),
                idx_local[:, :, None], axis=2)[:, :, 0]
            flat = flat + g_at * int(np.prod(spatial[d + 1:]))
        return val, flat.astype(jnp.int32)

    return run_op("max_pool_index", fn, [xx])


def _max_unpool(x, indices, kernel_size, stride, padding, output_size, n,
                name):
    xx = _t(x)
    k = _tuple(kernel_size, n)
    s = _tuple(stride if stride is not None else kernel_size, n)
    p = _pads(padding, n)
    if output_size is None:
        out_sp = tuple(
            (int(xx.shape[2 + d]) - 1) * s[d] - 2 * p[d][0] + k[d]
            for d in range(n))
    else:
        out_sp = tuple(int(v) for v in output_size[-n:])

    def fn(a, idx):
        B, C = a.shape[0], a.shape[1]
        flat_len = int(np.prod(out_sp))
        av = a.reshape(B, C, -1)
        iv = idx.reshape(B, C, -1).astype(jnp.int32)
        out = jnp.zeros((B, C, flat_len), a.dtype)
        out = jax.vmap(jax.vmap(
            lambda o, v, i: o.at[i].set(v)))(out, av, iv)
        return out.reshape(B, C, *out_sp)

    return run_op("max_unpool", fn, [xx, indices])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool1d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 1, name)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool2d (kernel
    unpool_kernel.cu)."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 2, name)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """reference nn/functional/pooling.py max_unpool3d."""
    return _max_unpool(x, indices, kernel_size, stride, padding,
                       output_size, 3, name)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """reference nn/functional/pooling.py lp_pool1d: (sum x^p)^(1/p)."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, ceil_mode,
                    1, data_format)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    """reference nn/functional/pooling.py lp_pool2d (ops.yaml lp_pool2d)."""
    return _lp_pool(x, norm_type, kernel_size, stride, padding, ceil_mode,
                    2, data_format)


def _lp_pool(x, norm_type, kernel_size, stride, padding, ceil_mode, n,
             data_format):
    xx = _t(x)
    pnorm = float(norm_type)

    def fn(a):
        if pnorm == float("inf"):
            raise ValueError("use max_pool for norm_type=inf")
        return jnp.abs(a) ** pnorm

    powed = run_op("lp_pow", fn, [xx])
    pooled = _pool(powed, kernel_size, stride, padding, n, jax.lax.add,
                   lambda dt: jnp.zeros((), dt), ceil_mode, True,
                   data_format, False)
    return run_op("lp_root",
                  lambda a: a ** (1.0 / pnorm), [pooled])


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference nn/functional/pooling.py fractional_max_pool2d (ops.yaml
    fractional_max_pool2d): pseudo-random bin boundaries from u."""
    return _fractional_pool(x, output_size, random_u, return_mask, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference fractional_max_pool3d."""
    return _fractional_pool(x, output_size, random_u, return_mask, 3)


def _fractional_pool(x, output_size, random_u, return_mask, n):
    xx = _t(x)
    out = _tuple(output_size, n)
    if random_u is None:
        from ...framework import random as rnd
        import jax.random as jrnd

        u = float(jrnd.uniform(rnd.next_key(), ()))
    else:
        u = float(random_u)
    spatial = [int(s) for s in xx.shape[2:]]
    # per-dim bin edges: alpha = in/out, edge_i = ceil(alpha*(i+u)) - ceil(alpha*u)
    sections = []
    for d in range(n):
        isz, osz = spatial[d], int(out[d])
        alpha = isz / osz
        base = int(np.ceil(alpha * u)) if u > 0 else 0
        edges = [int(np.ceil(alpha * (i + u))) - base for i in range(osz + 1)]
        edges[0] = 0
        edges[-1] = isz
        sections.append(edges)

    def fn(a):
        # pool dim by dim with variable bins (host-known boundaries);
        # per-bin max via explicit slicing (static shapes per bin)
        vals = a

        def pool_dim(v, edges, ax):
            outs = []
            for i in range(len(edges) - 1):
                sl = [slice(None)] * v.ndim
                sl[ax] = slice(edges[i], max(edges[i + 1], edges[i] + 1))
                outs.append(v[tuple(sl)].max(axis=ax, keepdims=True))
            return jnp.concatenate(outs, axis=ax)

        for d in range(n):
            vals = pool_dim(vals, sections[d], 2 + d)
        return vals

    pooled = run_op("fractional_max_pool", fn, [xx])
    if not return_mask:
        return pooled

    def mask_fn(a, pv):
        # recover argmax flat index per output bin (scan bins, compare)
        B, C = a.shape[0], a.shape[1]
        av = a.reshape(B, C, *spatial)
        out_shape = [int(o) for o in out]
        m = jnp.zeros((B, C, *out_shape), jnp.int32)
        import itertools as it

        for bins in it.product(*[range(len(s) - 1) for s in sections]):
            sl = [slice(None), slice(None)]
            offs = []
            for d, b in enumerate(bins):
                lo = sections[d][b]
                hi = max(sections[d][b + 1], lo + 1)
                sl.append(slice(lo, hi))
                offs.append(lo)
            region = av[tuple(sl)].reshape(B, C, -1)
            loc = jnp.argmax(region, axis=-1)
            shp = [sl[2 + d].stop - sl[2 + d].start for d in range(n)]
            coords = []
            rem = loc
            for d in range(n - 1, -1, -1):
                coords.append(rem % shp[d] + offs[d])
                rem = rem // shp[d]
            coords = coords[::-1]
            flat = jnp.zeros_like(loc)
            for d in range(n):
                flat = flat + coords[d] * int(np.prod(spatial[d + 1:]))
            m = m.at[(slice(None), slice(None), *bins)].set(
                flat.astype(jnp.int32))
        return m

    mask = run_op("fractional_max_pool_mask", mask_fn, [xx, pooled])
    return pooled, mask
