"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).
All lower to lax.reduce_window."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "avg_pool1d",
    "avg_pool2d",
    "avg_pool3d",
    "max_pool1d",
    "max_pool2d",
    "max_pool3d",
    "adaptive_avg_pool1d",
    "adaptive_avg_pool2d",
    "adaptive_avg_pool3d",
    "adaptive_max_pool1d",
    "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding!r}")


def _pool(x, kernel, stride, padding, n, reducer, init, ceil_mode, count_include_pad, data_format, is_avg):
    channels_last = not data_format.startswith("NC")
    k = _tuple(kernel, n)
    s = _tuple(stride if stride is not None else kernel, n)
    pads = _pads(padding, n)

    def fn(a):
        nd = a.ndim
        if channels_last:
            spatial = list(range(1, nd - 1))
        else:
            spatial = list(range(2, nd))
        window = [1] * nd
        strides = [1] * nd
        for i, ax in enumerate(spatial):
            window[ax] = k[i]
            strides[ax] = s[i]
        if isinstance(pads, str):
            padcfg = pads
        else:
            padcfg = [(0, 0)] * nd
            for i, ax in enumerate(spatial):
                padcfg[ax] = pads[i]
        if is_avg:
            ones = jnp.ones_like(a)
            summed = jax.lax.reduce_window(a, 0.0 if a.dtype != jnp.bfloat16 else jnp.bfloat16(0), jax.lax.add, window, strides, padcfg)
            if count_include_pad:
                denom = float(np.prod(k))
                return (summed / denom).astype(a.dtype)
            counts = jax.lax.reduce_window(ones, 0.0 if a.dtype != jnp.bfloat16 else jnp.bfloat16(0), jax.lax.add, window, strides, padcfg)
            return (summed / counts).astype(a.dtype)
        return jax.lax.reduce_window(a, init(a.dtype), reducer, window, strides, padcfg)

    return run_op("pool", fn, [_t(x)])


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add, None, ceil_mode, not exclusive, "NCL", True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add, None, ceil_mode, not exclusive, data_format, True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add, None, ceil_mode, not exclusive, data_format, True)


def _neg_inf(dtype):
    # must be a Python scalar literal: reduce_window's autodiff rule only
    # recognizes the max-monoid when init_value is the -inf constant
    return -np.inf if jnp.issubdtype(dtype, jnp.floating) else int(jnp.iinfo(dtype).min)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.max, _neg_inf, ceil_mode, False, "NCL", False)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.max, _neg_inf, ceil_mode, False, data_format, False)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.max, _neg_inf, ceil_mode, False, data_format, False)


def _adaptive(x, output_size, n, is_avg, data_format="NCHW"):
    xx = _t(x)
    out_sizes = _tuple(output_size, n)

    def fn(a):
        nd = a.ndim
        spatial = list(range(2, nd))
        out = a
        for i, ax in enumerate(spatial):
            osz = out_sizes[i]
            if osz is None:
                continue
            isz = out.shape[ax]
            if isz % osz == 0:
                k = isz // osz
                shape = out.shape[:ax] + (osz, k) + out.shape[ax + 1:]
                r = out.reshape(shape)
                out = jnp.mean(r, axis=ax + 1) if is_avg else jnp.max(r, axis=ax + 1)
            else:
                # general adaptive pooling: per-output-bin segments
                starts = (np.arange(osz) * isz) // osz
                ends = -(-((np.arange(osz) + 1) * isz) // osz)
                slices = []
                for st, en in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(st), int(en), axis=ax)
                    red = jnp.mean(seg, axis=ax, keepdims=True) if is_avg else jnp.max(seg, axis=ax, keepdims=True)
                    slices.append(red)
                out = jnp.concatenate(slices, axis=ax)
        return out

    return run_op("adaptive_pool", fn, [xx])


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, True, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, True, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, False)
