"""nn.functional tail (reference: python/paddle/nn/functional/ — vision.py
grid_sample/affine_grid, loss.py gaussian_nll/poisson_nll/soft_margin/
multi_label_soft_margin/triplet_margin_with_distance/npair/dice, common.py
sequence_mask/zeropad2d/pairwise_distance, extension.py gather_tree/
temporal_shift, flash_attention.py qkvpacked wrappers)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op, to_tensor

__all__ = [
    "affine_grid", "grid_sample", "pairwise_distance", "sequence_mask",
    "zeropad2d", "temporal_shift", "gather_tree", "dice_loss",
    "gaussian_nll_loss", "poisson_nll_loss", "soft_margin_loss",
    "multi_label_soft_margin_loss", "triplet_margin_with_distance_loss",
    "npair_loss", "flash_attn_qkvpacked", "flash_attn_varlen_qkvpacked",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


# --------------------------------------------------------------------------- #
# spatial transformer (reference vision.py affine_grid :33, grid_sample :276)
# --------------------------------------------------------------------------- #


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2]."""
    N, C, H, W = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1.0
            ys = (jnp.arange(H) * 2 + 1) / H - 1.0
        gx, gy = jnp.meshgrid(xs, ys)  # [H, W]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
        return jnp.einsum("nij,hwj->nhwi", th.astype(jnp.float32), base)

    return run_op("affine_grid", fn, [_t(theta)])


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N, C, H, W], grid [N, Hg, Wg, 2] in [-1, 1] -> [N, C, Hg, Wg]."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample: unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"grid_sample: unsupported padding {padding_mode!r}")

    def fn(v, g):
        N, C, H, W = v.shape
        gx = g[..., 0].astype(jnp.float32)
        gy = g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def reflect(f, n):
            if align_corners:
                span = 2 * (n - 1)
                f = jnp.abs(jnp.mod(f, span))
                return jnp.where(f > n - 1, span - f, f)
            span = 2 * n
            f = jnp.mod(jnp.abs(f + 0.5), span)
            f = jnp.where(f > n, span - f, f) - 0.5
            return jnp.clip(f, 0, n - 1)

        if padding_mode == "reflection":
            fx = reflect(fx, W)
            fy = reflect(fy, H)

        def sample(ix, iy):
            """Gather with out-of-range handling -> [N, Hg, Wg, C]."""
            inside = ((ix >= 0) & (ix <= W - 1) & (iy >= 0) & (iy <= H - 1))
            cx = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
            cy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
            vals = jax.vmap(
                lambda img, yy, xx: img[:, yy, xx])(v, cy, cx)  # [N,C,Hg,Wg]
            if padding_mode == "zeros":
                vals = jnp.where(inside[:, None], vals, 0.0)
            return vals

        if mode == "nearest":
            return sample(jnp.round(fx), jnp.round(fy))

        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        x1, y1 = x0 + 1, y0 + 1
        wx1 = fx - x0
        wy1 = fy - y0
        wx0 = 1 - wx1
        wy0 = 1 - wy1
        out = (sample(x0, y0) * (wx0 * wy0)[:, None]
               + sample(x1, y0) * (wx1 * wy0)[:, None]
               + sample(x0, y1) * (wx0 * wy1)[:, None]
               + sample(x1, y1) * (wx1 * wy1)[:, None])
        return out.astype(v.dtype)

    return run_op("grid_sample", fn, [_t(x), _t(grid)])


# --------------------------------------------------------------------------- #
# common
# --------------------------------------------------------------------------- #


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)

    return run_op("pairwise_distance", fn, [_t(x), _t(y)])


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths [.., B] -> [..., maxlen] 0/1 mask (reference common.py)."""
    t = _t(x)
    import numpy as np

    if maxlen is not None:
        ml = int(maxlen)
    else:
        if isinstance(t._value, jax.core.Tracer):
            raise ValueError(
                "sequence_mask: maxlen=None needs a concrete lengths tensor "
                "(it sets the output shape); pass maxlen explicitly under "
                "jit/to_static")
        ml = int(np.asarray(t._value).max())
    from ...framework.dtype import convert_dtype

    nd = convert_dtype(dtype)
    if str(nd) == "int64" and not jax.config.jax_enable_x64:
        nd = jnp.int32  # avoid the per-call truncation warning

    def fn(v):
        rng = jnp.arange(ml)
        return (rng < v[..., None]).astype(nd)

    return run_op("sequence_mask", fn, [t])


def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, top, bot = [int(p) for p in padding]

    def fn(v):
        if data_format == "NCHW":
            return jnp.pad(v, ((0, 0), (0, 0), (top, bot), (l, r)))
        return jnp.pad(v, ((0, 0), (top, bot), (l, r), (0, 0)))

    return run_op("zeropad2d", fn, [_t(x)])


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM shift (reference extension.py temporal_shift)."""
    def fn(v):
        if data_format != "NCHW":
            v = jnp.moveaxis(v, -1, 1)
        NT, C, H, W = v.shape
        N = NT // seg_num
        v5 = v.reshape(N, seg_num, C, H, W)
        fold = int(C * shift_ratio)
        back = jnp.roll(v5[:, :, :fold], -1, axis=1).at[:, -1, :].set(0.0)
        fwd = jnp.roll(v5[:, :, fold:2 * fold], 1, axis=1).at[:, 0, :].set(0.0)
        keep = v5[:, :, 2 * fold:]
        out = jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)
        if data_format != "NCHW":
            out = jnp.moveaxis(out, 1, -1)
        return out

    return run_op("temporal_shift", fn, [_t(x)])


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace [T, B, K] (reference extension.py gather_tree;
    kernel phi/kernels/gather_tree_kernel)."""
    def fn(idv, par):
        T = idv.shape[0]

        def step(carry, xs):
            beam = carry  # [B, K] beam index at time t+1
            ids_t, par_t = xs
            out = jnp.take_along_axis(ids_t, beam, axis=-1)
            beam_prev = jnp.take_along_axis(par_t, beam, axis=-1)
            return beam_prev.astype(beam.dtype), out

        init = jnp.broadcast_to(jnp.arange(idv.shape[-1], dtype=jnp.int32),
                                idv.shape[1:])
        _, outs = jax.lax.scan(step, init, (idv[::-1], par[::-1]))
        return outs[::-1]

    return run_op("gather_tree", fn, [_t(ids), _t(parents)])


# --------------------------------------------------------------------------- #
# loss tail
# --------------------------------------------------------------------------- #


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """reference loss.py dice_loss — input [N, ..., C] probs, label
    [N, ..., 1] class ids."""
    def fn(p, lab):
        C = p.shape[-1]
        one_hot = jax.nn.one_hot(lab[..., 0].astype(jnp.int32), C,
                                 dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * one_hot, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(one_hot, axis=red)
        # epsilon in the denominator only (reference loss.py dice_loss)
        return jnp.mean(1 - 2 * inter / (union + epsilon))

    return run_op("dice_loss", fn, [_t(input), _t(label)])


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,  # noqa: A002
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            import math

            loss = loss + 0.5 * math.log(2 * math.pi)
        return _reduce(loss, reduction)

    return run_op("gaussian_nll_loss", fn,
                  [_t(input), _t(label), _t(variance)])


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return run_op("poisson_nll_loss", fn, [_t(input), _t(label)])


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def fn(x, y):
        # softplus form: log1p(exp(z)) overflows f32 past z ~ 89
        return _reduce(jax.nn.softplus(-y.astype(x.dtype) * x), reduction)

    return run_op("soft_margin_loss", fn, [_t(input), _t(label)])


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    ins = [_t(input), _t(label)] + ([_t(weight)] if weight is not None else [])

    def fn(x, y, *rest):
        y = y.astype(x.dtype)
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if rest:
            loss = loss * rest[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)

    return run_op("multi_label_soft_margin_loss", fn, ins)


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (
        lambda a, b: pairwise_distance(a, b))

    def raw(a, b):
        out = dist(a, b)
        return out._value if isinstance(out, Tensor) else out

    def fn(a, p, n):
        d_ap = raw(Tensor(a), Tensor(p))
        d_an = raw(Tensor(a), Tensor(n))
        if swap:
            d_pn = raw(Tensor(p), Tensor(n))
            d_an = jnp.minimum(d_an, d_pn)
        return _reduce(jnp.maximum(d_ap - d_an + margin, 0.0), reduction)

    return run_op("triplet_margin_with_distance_loss", fn,
                  [_t(input), _t(positive), _t(negative)])


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference loss.py npair_loss."""
    def fn(a, p, y):
        B = a.shape[0]
        sim = a @ p.T  # [B, B]
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(same * logp, axis=1))
        # reference uses Beta = 0.25 * l2_reg
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return ce + reg

    return run_op("npair_loss", fn, [_t(anchor), _t(positive), _t(labels)])


# --------------------------------------------------------------------------- #
# qkv-packed flash attention wrappers
# --------------------------------------------------------------------------- #


def _unpack_qkv(t, axis):
    # ONE dispatch for all three slices (run_op supports tuple outputs)
    def fn(v):
        return (jnp.take(v, 0, axis=axis), jnp.take(v, 1, axis=axis),
                jnp.take(v, 2, axis=axis))

    return run_op("qkv_unpack", fn, [t], n_outputs=3)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         *args, **kwargs):
    """qkv [B, S, 3, H, D] (reference flash_attention.py
    flash_attn_qkvpacked) — unpacks and routes to flash_attention."""
    from .flash_attention import flash_attention

    q, k, v = _unpack_qkv(_t(qkv), axis=2)
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale, dropout=0.0,
                                causal=False, varlen_padded=True,
                                return_softmax=False, **kwargs):
    """qkv [T, 3, H, D] PACKED varlen (reference
    flash_attn_varlen_qkvpacked). The reference's default varlen_padded=True
    layout ([B*maxlen, ...] with padding rows) is a different memory
    convention — silently reading it as packed would misalign every
    sequence, so it must be disabled explicitly."""
    if varlen_padded:
        raise NotImplementedError(
            "flash_attn_varlen_qkvpacked: the padded [B*maxlen, 3, H, D] "
            "layout is not supported; pass varlen_padded=False with densely "
            "packed tokens")
    from .flash_attention import flash_attn_unpadded

    q, k, v = _unpack_qkv(_t(qkv), axis=1)
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k, scale,
                               dropout=dropout, causal=causal,
                               return_softmax=return_softmax)
