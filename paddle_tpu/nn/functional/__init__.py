"""nn.functional namespace (reference: python/paddle/nn/functional/)."""

from . import activation, common, conv, loss, norm, pooling
from . import flash_attention as _flash_attention_mod

__all__ = (
    list(activation.__all__)
    + list(common.__all__)
    + list(conv.__all__)
    + list(pooling.__all__)
    + list(norm.__all__)
    + list(loss.__all__)
    + list(_flash_attention_mod.__all__)
)

from .activation import *  # noqa: F401,F403,E402
from .common import *  # noqa: F401,F403,E402
from .conv import *  # noqa: F401,F403,E402
from .flash_attention import *  # noqa: F401,F403,E402
from .loss import *  # noqa: F401,F403,E402
from .norm import *  # noqa: F401,F403,E402
from .pooling import *  # noqa: F401,F403,E402

from .extras import *  # noqa: F401,F403,E402
from . import extras as _extras  # noqa: E402
__all__ = list(__all__) + list(_extras.__all__)
