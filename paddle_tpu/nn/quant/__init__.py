"""Weight-only / llm.int8 quantized inference ops (reference:
python/paddle/nn/quant/quantized_linear.py; kernels
phi/kernels/gpu/weight_only_linear_kernel.cu, llm_int8_linear).

TPU formulation: int8/int4 weights live in HBM at 1/2–1/4 the bytes; the
matmul dequantizes inline (int8 * per-channel scale) so XLA fuses the
upcast into the MXU feed — the bandwidth saving is the same one the
reference's CUTLASS kernels chase. int4 packs two nibbles per int8 byte.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, run_op

__all__ = [
    "quantize_for_inference",
    "weight_quantize",
    "weight_dequantize",
    "weight_only_linear",
    "llm_int8_linear",
]


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """Quantize [in, out] weights; returns (packed int8 [out, in] (int4:
    [out, in/2]), per-channel float32 scale [out]) — the reference's
    transposed layout (quantized_linear.py:64)."""
    if algo not in ("weight_only_int8", "weight_only_int4", "llm.int8"):
        raise ValueError(f"unknown algo {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError("group_size must be -1, 64 or 128")
    int4 = algo == "weight_only_int4"
    in_features = int(x.shape[0])
    if int4 and in_features % 2 != 0:
        raise ValueError(
            f"weight_only_int4 packs two values per byte; in_features "
            f"must be even, got {in_features}")
    if group_size > 0 and in_features % group_size != 0:
        raise ValueError(
            f"in_features {in_features} not divisible by group_size "
            f"{group_size}")

    def fn(w):
        wt = w.astype(jnp.float32).T  # [out, in]
        if group_size == -1:
            maxabs = jnp.max(jnp.abs(wt), axis=1, keepdims=True)
            bound = 7.0 if int4 else 127.0
            scale = maxabs / bound
            q = jnp.clip(jnp.round(wt / jnp.maximum(scale, 1e-8)),
                         -bound - 1, bound)
            scale_out = scale[:, 0]
        else:
            O, I = wt.shape
            g = wt.reshape(O, I // group_size, group_size)
            maxabs = jnp.max(jnp.abs(g), axis=2, keepdims=True)
            bound = 7.0 if int4 else 127.0
            scale = maxabs / bound
            q = jnp.clip(jnp.round(g / jnp.maximum(scale, 1e-8)),
                         -bound - 1, bound).reshape(O, I)
            scale_out = scale[:, :, 0]  # [out, n_groups]
        qi = q.astype(jnp.int8)
        if int4:
            # pack 2 nibbles per byte along the in dim
            lo = qi[:, 0::2] & 0xF
            hi = (qi[:, 1::2] & 0xF) << 4
            qi = (lo | hi).astype(jnp.int8)
        return qi, scale_out.astype(jnp.float32)

    return run_op("weight_quantize", fn, [x])


def _unpack_int4(q):
    lo = (q << 4).astype(jnp.int8) >> 4  # sign-extend low nibble
    hi = q >> 4                           # arithmetic shift keeps sign
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(q.shape[0], q.shape[1] * 2)


def _dequant(qw, scale, algo, out_dtype):
    w = _unpack_int4(qw) if algo == "weight_only_int4" else qw
    wf = w.astype(jnp.float32)
    if scale.ndim == 1:
        wf = wf * scale[:, None]
    else:  # grouped [out, n_groups]
        O, I = wf.shape
        g = I // scale.shape[1]
        wf = (wf.reshape(O, scale.shape[1], g)
              * scale[:, :, None]).reshape(O, I)
    return wf.astype(out_dtype)


def weight_dequantize(x, scale, algo="weight_only_int8",
                      out_dtype="float16"):
    """Inverse of weight_quantize; returns [in, out]
    (quantized_linear.py:131)."""
    from ...framework.dtype import convert_dtype

    dt = jnp.dtype(convert_dtype(out_dtype))

    def fn(q, s):
        return _dequant(q, s, algo, dt).T

    return run_op("weight_dequantize", fn, [x, scale])


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x @ dequant(weight)^T + bias with int8/int4 weights
    (quantized_linear.py:191). The dequant fuses into the matmul feed."""
    algo = "weight_only_int4" if weight_dtype == "int4" else \
        "weight_only_int8"

    def fn(xv, qw, s, *rest):
        b = rest[0] if rest else None
        wf = _dequant(qw, s, algo, xv.dtype)  # [out, in]
        out = xv @ wf.T
        if b is not None:
            out = out + b
        return out

    ins = [x, weight, weight_scale]
    if bias is not None:
        ins.append(bias)
    return run_op("weight_only_linear", fn, ins)


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8(): outlier activation columns run in fp, the rest int8
    (quantized_linear.py:285; arXiv:2208.07339)."""
    def fn(xv, qw, s, *rest):
        b = rest[0] if rest else None
        absx = jnp.max(jnp.abs(xv), axis=tuple(range(xv.ndim - 1)))
        outlier = absx > threshold  # [in]
        wf = qw.astype(jnp.float32) * s[:, None]  # [out, in]
        # int8 path: quantize non-outlier activations per-row
        xm = jnp.where(outlier, 0.0, xv)
        xs = jnp.max(jnp.abs(xm), axis=-1, keepdims=True) / 127.0
        xq = jnp.clip(jnp.round(xm / jnp.maximum(xs, 1e-8)), -128, 127)
        main = (xq @ jnp.where(outlier[None, :], 0.0, wf).T) * xs
        outl = jnp.where(outlier, xv, 0.0) @ \
            jnp.where(outlier[None, :], wf, 0.0).T
        out = (main + outl).astype(xv.dtype)
        if b is not None:
            out = out + b
        return out

    ins = [x, weight, weight_scale]
    if bias is not None:
        ins.append(bias)
    return run_op("llm_int8_linear", fn, ins)


def quantize_for_inference(layer, algo="weight_only_int8", group_size=-1,
                           min_features=64):
    """Convert a trained model's Linear sublayers to weight-only quantized
    inference form IN PLACE (the reference flow: paddle.nn.quant
    weight_quantize applied per layer by the serving stack).

    Each eligible ``nn.Linear`` keeps int8/int4 packed weights + scales as
    BUFFERS (the fp32 weight parameter is dropped — HBM shrinks 4-8x) and
    its forward becomes ``weight_only_linear``. Layers smaller than
    `min_features` on either dim stay fp (quantization noise dominates).
    Returns the converted layer count."""
    from .. import Linear
    from ...distributed.fleet.layers.mpu.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    eligible = (Linear, ColumnParallelLinear, RowParallelLinear)
    wdtype = "int4" if algo == "weight_only_int4" else "int8"
    n = 0
    for _name, sub in layer.named_sublayers(include_self=True):
        if not isinstance(sub, eligible) or sub.weight is None:
            continue
        if getattr(sub, "is_mp", False):
            # sharded layers keep their collective forward; weight-only
            # conversion targets single-device serving
            continue
        in_f, out_f = int(sub.weight.shape[0]), int(sub.weight.shape[1])
        if in_f < min_features or out_f < min_features:
            continue
        if algo == "weight_only_int4" and in_f % 2:
            continue
        if group_size > 0 and in_f % group_size:
            continue  # same precondition weight_quantize enforces —
            # skipping keeps the in-place conversion atomic per layer
        q, s = weight_quantize(sub.weight, algo=algo, group_size=group_size)
        del sub._parameters["weight"]
        object.__setattr__(sub, "weight", None)
        sub.register_buffer("weight_quant", q)
        sub.register_buffer("weight_scale", s)

        def _q_forward(x, _sub=sub, _dt=wdtype):
            return weight_only_linear(
                x, _sub.weight_quant, bias=_sub.bias,
                weight_scale=_sub.weight_scale, weight_dtype=_dt)

        sub.forward = _q_forward
        n += 1
    return n
