"""paddle_tpu.nn (reference: python/paddle/nn/)."""

from . import functional, initializer, quant, utils
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import layers as _layers_mod
from .layer.layers import Layer, ParamAttr  # noqa: F401

__all__ = ["Layer", "ParamAttr", "functional", "initializer",
           "ClipGradByGlobalNorm", "ClipGradByNorm", "ClipGradByValue"]
