"""Parameter reparameterization utilities (reference:
python/paddle/nn/utils/ — weight_norm_hook.py, spectral_norm_hook.py,
transform_parameters.py, clip_grad_norm_.py).

Both norms install a forward PRE-hook that recomputes the effective
weight from auxiliary parameters before every forward — the same hook
design as the reference; the recompute is a couple of fused reductions
XLA folds into the surrounding graph.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...framework.core import Tensor, run_op, to_tensor
from ..layer.layers import Layer

__all__ = [
    "weight_norm",
    "remove_weight_norm",
    "spectral_norm",
    "parameters_to_vector",
    "vector_to_parameters",
    "clip_grad_norm_",
    "clip_grad_value_",
]


def _norm_except(w, dim):
    """L2 norm over all axes except `dim` (keeps dims)."""
    axes = tuple(i for i in range(len(w.shape)) if i != dim)
    return run_op(
        "norm_except",
        lambda a: jnp.sqrt(jnp.sum(a * a, axis=axes, keepdims=True)),
        [w])


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize layer.<name> as g * v / ||v|| (reference
    weight_norm_hook.py). Adds <name>_g and <name>_v parameters."""
    if dim is None:
        dim = -1
    w = getattr(layer, name)
    ndim = len(w.shape)
    if dim < 0:
        dim += ndim
    g = _norm_except(w, dim)
    from ...framework.core import Parameter

    gp = Parameter(g._value, trainable=True)
    vp = Parameter(w._value, trainable=True)
    del layer._parameters[name]
    layer._parameters[name + "_g"] = gp
    layer._parameters[name + "_v"] = vp
    object.__setattr__(layer, name + "_g", gp)
    object.__setattr__(layer, name + "_v", vp)

    def compute():
        vn = _norm_except(vp, dim)
        eff = run_op("weight_norm_eff",
                     lambda vv, gg, nn_: vv * (gg / jnp.maximum(nn_, 1e-12)),
                     [vp, gp, vn])
        object.__setattr__(layer, name, eff)

    def hook(lyr, inputs):
        compute()
        return None

    compute()
    h = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hook = (h, name, dim)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter (reference
    weight_norm_hook.py remove_weight_norm)."""
    h, nm, dim = layer._weight_norm_hook
    h.remove()
    from ...framework.core import Parameter

    gp = layer._parameters.pop(nm + "_g")
    vp = layer._parameters.pop(nm + "_v")
    vn = _norm_except(vp, dim)
    eff = run_op("weight_norm_eff",
                 lambda vv, gg, nn_: vv * (gg / jnp.maximum(nn_, 1e-12)),
                 [vp, gp, vn])
    p = Parameter(eff._value, trainable=True)
    layer._parameters[nm] = p
    object.__setattr__(layer, nm, p)
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide layer.<name> by its largest singular value, estimated with
    power iteration on persistent u/v buffers (reference
    spectral_norm_hook.py; kernel spectral_norm op in ops.yaml)."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    shape = [int(s) for s in w.shape]
    h = shape[dim]
    rest = int(np.prod(shape)) // h
    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(h).astype("float32")
    v0 = rng.standard_normal(rest).astype("float32")
    u0 /= np.linalg.norm(u0) + eps
    v0 /= np.linalg.norm(v0) + eps
    layer.register_buffer(name + "_u", to_tensor(u0))
    layer.register_buffer(name + "_v", to_tensor(v0))
    from ...framework.core import Parameter

    orig = Parameter(w._value, trainable=True)
    del layer._parameters[name]
    layer._parameters[name + "_orig"] = orig
    object.__setattr__(layer, name + "_orig", orig)

    def compute(update_iters):
        ub = getattr(layer, name + "_u")
        vb = getattr(layer, name + "_v")

        def fn(wv, u, v):
            wm = jnp.moveaxis(wv, dim, 0).reshape(h, rest)
            for _ in range(update_iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return wv / sigma, u, v

        eff, nu, nv = run_op("spectral_norm", fn, [orig, ub, vb])
        ub._value = nu._value
        vb._value = nv._value
        object.__setattr__(layer, name, eff)

    def hook(lyr, inputs):
        compute(n_power_iterations if lyr.training else 0)
        return None

    compute(n_power_iterations)
    layer.register_forward_pre_hook(hook)
    return layer


def parameters_to_vector(parameters, name=None):
    """Concat flattened params (reference transform_parameters.py)."""
    params = list(parameters)

    def fn(*vals):
        return jnp.concatenate([v.reshape(-1) for v in vals])

    return run_op("params_to_vector", fn, params)


def vector_to_parameters(vec, parameters, name=None):
    """Scatter a flat vector back into params (in-place)."""
    params = list(parameters)
    off = 0
    v = np.asarray(vec._value if isinstance(vec, Tensor) else vec)
    for p in params:
        n = int(np.prod(p.shape))
        p._value = jnp.asarray(v[off:off + n].reshape(tuple(p.shape)),
                               p._value.dtype)
        off += n
    return params


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Clip total grad norm in place; returns the pre-clip norm
    (reference clip_grad_norm_.py)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return to_tensor(np.float32(0.0))
    # one fused device reduction + a single scalar read — per-step hot
    # path must not pull every grad to host
    if norm_type == float("inf"):
        total_dev = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total_dev = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads])
        ) ** (1.0 / norm_type)
    total = float(total_dev)
    if error_if_nonfinite and not np.isfinite(total):
        raise RuntimeError(
            f"grad norm is {total}; set error_if_nonfinite=False to skip")
    coef = max_norm / (total + 1e-6)
    if coef < 1.0:
        for p in parameters:
            if p.grad is not None:
                p.grad._value = p.grad._value * coef
    return to_tensor(np.float32(total))


def clip_grad_value_(parameters, clip_value):
    """Clamp each grad element to [-clip_value, clip_value]."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)
