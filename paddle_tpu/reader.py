"""Legacy reader decorators (reference: python/paddle/reader/decorator.py
and paddle.batch). Generator-based pipelines predating DataLoader; kept
because tutorial-era training scripts compose them."""

from __future__ import annotations

import random as _random

__all__ = ["batch", "shuffle", "buffered", "chain", "compose", "map_readers",
           "cache", "firstn"]


def batch(reader, batch_size, drop_last=False):
    """reference python/paddle/batch.py — group samples into lists."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return gen


def shuffle(reader, buf_size):
    """reference reader/decorator.py shuffle."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf

    return gen


def buffered(reader, size):
    """reference reader/decorator.py buffered — here an eager list buffer
    (host threads add nothing: the DataLoader owns async prefetch)."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= size:
                yield from buf
                buf = []
        yield from buf

    return gen


def chain(*readers):
    def gen():
        for r in readers:
            yield from r()

    return gen


def compose(*readers, check_alignment=True):
    def gen():
        iters = [r() for r in readers]
        while True:
            outs = []
            stop = 0
            for it in iters:
                try:
                    outs.append(next(it))
                except StopIteration:
                    stop += 1
            if stop:
                if check_alignment and 0 < stop < len(iters):
                    raise ValueError("readers have different lengths")
                return
            # flatten: tuples from each reader concatenate (reference
            # compose semantics)
            yield tuple(sum(((o if isinstance(o, tuple) else (o,))
                             for o in outs), ()))

    return gen


def map_readers(func, *readers):
    def gen():
        for args in zip(*[r() for r in readers]):
            yield func(*args)

    return gen


def cache(reader):
    data = []
    filled = [False]

    def gen():
        if not filled[0]:
            for item in reader():
                data.append(item)
                yield item
            filled[0] = True
        else:
            yield from data

    return gen


def firstn(reader, n):
    def gen():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return gen
