"""Audio features (reference: python/paddle/audio/ — functional/functional.py
hz_to_mel/mel_to_hz/compute_fbank_matrix/create_dct/power_to_db,
features/layers.py Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC).

TPU formulation: everything composes from the fft module (XLA FftOp) plus
dense matmuls — framing via strided gather, mel projection as one [freq,
mel] matmul the MXU eats. All layers are differentiable run_ops."""

from . import functional
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]

from . import datasets  # noqa: E402
from . import backends  # noqa: E402
from .backends import load, save, info  # noqa: E402
