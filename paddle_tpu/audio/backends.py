"""Audio file IO (reference: python/paddle/audio/backends/ — wave_backend
load/save/info over the soundfile/wave libraries). Host-side scipy/wave IO;
waveforms land as float32 arrays ready for `to_tensor`."""

from __future__ import annotations

import wave

import numpy as np

from ..framework.core import Tensor, to_tensor

__all__ = ["load", "save", "info", "AudioInfo"]


class AudioInfo:
    """reference backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Read a wav file -> (Tensor [C, T] (channels_first) float32 in
    [-1, 1], sample_rate) (reference wave_backend.load)."""
    from scipy.io import wavfile

    sr, data = wavfile.read(filepath)
    if data.ndim == 1:
        data = data[:, None]
    data = data[frame_offset: None if num_frames < 0
                else frame_offset + num_frames]
    if normalize:
        if data.dtype.kind == "i":
            data = data.astype(np.float32) / np.iinfo(data.dtype).max
        elif data.dtype.kind == "u":
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32)
    arr = data.T if channels_first else data
    return to_tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Write a float waveform as PCM wav (reference wave_backend.save).
    Supports 16- and 32-bit signed PCM; rejects other encodings rather
    than silently down-converting."""
    if encoding not in ("PCM_16", "PCM_32") \
            or bits_per_sample not in (16, 32) \
            or (encoding == "PCM_16") != (bits_per_sample == 16):
        raise ValueError(
            f"unsupported encoding {encoding}/{bits_per_sample}; "
            "supported: PCM_16/16, PCM_32/32")
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if arr.ndim == 1:
        frames = arr[:, None]  # mono [T] -> [T, 1] regardless of layout
    else:
        frames = arr.T if channels_first else arr  # -> [T, C]
    # scale in float64: float32 * INT32_MAX rounds to 2^31 and would wrap
    pcm = np.clip(frames.astype(np.float64), -1.0, 1.0)
    if bits_per_sample == 16:
        pcm = np.clip(pcm * 32767.0, -32768, 32767).astype("<i2")
        width = 2
    else:
        pcm = np.clip(pcm * 2147483647.0,
                      -2147483648, 2147483647).astype("<i4")
        width = 4
    with wave.open(str(filepath), "wb") as w:
        w.setnchannels(pcm.shape[1])
        w.setsampwidth(width)
        w.setframerate(int(sample_rate))
        w.writeframes(pcm.tobytes())


def info(filepath):
    """Header-only probe (reference wave_backend.info)."""
    with wave.open(str(filepath), "rb") as w:
        return AudioInfo(sample_rate=w.getframerate(),
                         num_samples=w.getnframes(),
                         num_channels=w.getnchannels(),
                         bits_per_sample=8 * w.getsampwidth())
