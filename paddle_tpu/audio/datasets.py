"""Local-file audio dataset loaders (reference: python/paddle/audio/
datasets/tess.py, esc50.py — download zoos; here the same on-disk layouts
read from user paths, zero-egress)."""

from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["TESS", "ESC50"]


def _read_wav(path):
    from scipy.io import wavfile

    sr, data = wavfile.read(path)
    if data.dtype.kind == "i":
        data = data.astype(np.float32) / np.iinfo(data.dtype).max
    elif data.dtype.kind == "u":
        data = (data.astype(np.float32) - 128.0) / 128.0
    else:
        data = data.astype(np.float32)
    if data.ndim > 1:
        data = data.mean(axis=1)
    return data, sr


class TESS(Dataset):
    """Toronto emotional speech set (reference audio/datasets/tess.py).
    Reads `<root>/**/<anything>_<word>_<emotion>.wav`; labels are the seven
    emotions in the reference's ordering."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, data_dir=None, mode="train", n_folds=5,
                 split=1, feat_type="raw", download=False, **kw):
        if download or data_dir is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_dir")
        self.feat_type = feat_type
        self.feat_kw = kw
        files = []
        for base, _dirs, names in os.walk(data_dir):
            for n in sorted(names):
                if n.lower().endswith(".wav"):
                    emo = n.rsplit(".", 1)[0].rsplit("_", 1)[-1].lower()
                    if emo in self.EMOTIONS:
                        files.append((os.path.join(base, n),
                                      self.EMOTIONS.index(emo)))
        fold_of = lambda i: i % n_folds + 1  # noqa: E731
        if mode == "train":
            self.files = [f for i, f in enumerate(files)
                          if fold_of(i) != split]
        else:
            self.files = [f for i, f in enumerate(files)
                          if fold_of(i) == split]

    def _features(self, wav, sr):
        if self.feat_type == "raw":
            return wav
        from . import features as AF
        from ..framework.core import to_tensor

        layer = {"melspectrogram": AF.MelSpectrogram,
                 "mfcc": AF.MFCC,
                 "logmelspectrogram": AF.LogMelSpectrogram,
                 "spectrogram": AF.Spectrogram}[self.feat_type](
            sr=sr, **self.feat_kw)
        return np.asarray(layer(to_tensor(wav[None])).numpy())[0]

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        path, label = self.files[idx]
        wav, sr = _read_wav(path)
        return self._features(wav, sr), np.int64(label)


class ESC50(Dataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py).
    Reads the standard layout `<root>/audio/<fold>-*-<target>.csv|wav` via
    `<root>/meta/esc50.csv`."""

    def __init__(self, data_dir=None, mode="train", split=1,
                 feat_type="raw", download=False, **kw):
        if download or data_dir is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_dir")
        meta = os.path.join(data_dir, "meta", "esc50.csv")
        audio_dir = os.path.join(data_dir, "audio")
        self.feat_type = feat_type
        self.feat_kw = kw
        rows = []
        with open(meta) as f:
            header = f.readline().strip().split(",")
            fi = header.index("filename")
            fo = header.index("fold")
            tg = header.index("target")
            for ln in f:
                c = ln.strip().split(",")
                rows.append((c[fi], int(c[fo]), int(c[tg])))
        if mode == "train":
            keep = [(fn, t) for fn, fold, t in rows if fold != split]
        else:
            keep = [(fn, t) for fn, fold, t in rows if fold == split]
        self.files = [(os.path.join(audio_dir, fn), t) for fn, t in keep]

    _features = TESS._features

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        path, label = self.files[idx]
        wav, sr = _read_wav(path)
        return self._features(wav, sr), np.int64(label)
