"""Audio functional ops (reference: python/paddle/audio/functional/
functional.py and window.py get_window)."""

from __future__ import annotations

import math

import numpy as np

import jax.numpy as jnp

from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "create_dct", "power_to_db", "get_window",
]


def hz_to_mel(freq, htk=False):
    """reference functional.py hz_to_mel (slaney default)."""
    scalar = not isinstance(freq, (Tensor, np.ndarray, list))
    f = np.asarray(freq._value if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                       mel)
    return float(mel) if scalar else mel


def mel_to_hz(mel, htk=False):
    """reference functional.py mel_to_hz."""
    scalar = not isinstance(mel, (Tensor, np.ndarray, list))
    m = np.asarray(mel._value if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar else f


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, 1 + n_fft//2] triangular mel filterbank (reference
    functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    fb = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference functional.py create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.T.astype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """reference functional.py power_to_db."""
    t = spect if isinstance(spect, Tensor) else to_tensor(spect)

    def fn(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(x, amin))
        log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec

    return run_op("power_to_db", fn, [t])


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """reference window.py get_window (hann/hamming/blackman/ones)."""
    n = np.arange(win_length)
    den = win_length if fftbins else win_length - 1
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * n / den)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * n / den)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * n / den)
             + 0.08 * np.cos(4 * math.pi * n / den))
    elif window in ("ones", "boxcar", "rectangular"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))
