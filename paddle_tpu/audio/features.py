"""Audio feature layers (reference: python/paddle/audio/features/layers.py —
Spectrogram :33, MelSpectrogram :117, LogMelSpectrogram :219, MFCC :315)."""

from __future__ import annotations

import jax.numpy as jnp

import paddle_tpu.nn as nn
from ..framework.core import Tensor, run_op, to_tensor
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _frame(x, frame_length, hop_length):
    """[..., T] -> [..., n_frames, frame_length] via strided gather."""
    n = (x.shape[-1] - frame_length) // hop_length + 1
    idx = (jnp.arange(n)[:, None] * hop_length
           + jnp.arange(frame_length)[None, :])
    return x[..., idx]


class Spectrogram(nn.Layer):
    """STFT power spectrogram [..., 1 + n_fft//2, n_frames]
    (reference layers.py:33)."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = F.get_window(window, self.win_length, dtype=dtype)._value
        if self.win_length < n_fft:  # center-pad the window to n_fft
            lp = (n_fft - self.win_length) // 2
            w = jnp.pad(w, (lp, n_fft - self.win_length - lp))
        self.register_buffer("window", Tensor(w))

    def forward(self, x):
        t = x if isinstance(x, Tensor) else to_tensor(x)
        n_fft, hop = self.n_fft, self.hop_length
        center, pad_mode, power = self.center, self.pad_mode, self.power

        def fn(v, w):
            if center:
                pad = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
                v = jnp.pad(v, pad, mode=pad_mode)
            frames = _frame(v, n_fft, hop) * w
            spec = jnp.fft.rfft(frames, axis=-1)
            mag = jnp.abs(spec)
            if power != 1.0:
                mag = mag ** power
            return jnp.swapaxes(mag, -1, -2)  # [..., freq, time]

        return run_op("spectrogram", fn, [t, self.window])


class MelSpectrogram(nn.Layer):
    """reference layers.py:117."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.register_buffer(
            "fbank", F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                            htk, norm, dtype))

    def forward(self, x):
        spec = self.spectrogram(x)
        return run_op("mel_project",
                      lambda s, fb: jnp.einsum("...ft,mf->...mt", s, fb),
                      [spec, self.fbank])


class LogMelSpectrogram(nn.Layer):
    """reference layers.py:219."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self.ref_value, self.amin,
                             self.top_db)


class MFCC(nn.Layer):
    """reference layers.py:315."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 n_mels=64, f_min=50.0, f_max=None, top_db=None,
                 dtype="float32", **kw):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_fft=n_fft,
                                        hop_length=hop_length, n_mels=n_mels,
                                        f_min=f_min, f_max=f_max,
                                        top_db=top_db, dtype=dtype, **kw)
        self.register_buffer("dct", F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        lm = self.logmel(x)
        return run_op("mfcc_dct",
                      lambda s, d: jnp.einsum("...mt,mc->...ct", s, d),
                      [lm, self.dct])
