"""Graph learning ops (reference: python/paddle/geometric/).

TPU design: segment reductions and gather-scatter message passing map
directly onto ``jax.ops.segment_*`` — XLA lowers them to sorted-scatter
fusions, which is the TPU-efficient formulation of the reference's CUDA
atomics kernels (phi/kernels/gpu/graph_send_recv_kernel.cu,
segment_pool_kernel.cu). Neighbor sampling and reindexing have
data-dependent output sizes, so they run host-side (matching their
CPU-bound role in GNN data pipelines).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, run_op, to_tensor

__all__ = [
    "segment_sum",
    "segment_mean",
    "segment_min",
    "segment_max",
    "send_u_recv",
    "send_ue_recv",
    "send_uv",
    "reindex_graph",
    "sample_neighbors",
]


def _np(x):
    return np.asarray(x._value if isinstance(x, Tensor) else x)


def _n_segments(segment_ids, out_size=None):
    if out_size is not None:
        return int(out_size if not isinstance(out_size, Tensor)
                   else _np(out_size))
    ids = _np(segment_ids)
    return int(ids.max()) + 1 if ids.size else 0


def _segment(op_name, jop, x, segment_ids, n):
    def fn(xv, ids):
        return jop(xv, ids.astype(jnp.int32), num_segments=n)

    return run_op(op_name, fn, [x, segment_ids])


def segment_sum(data, segment_ids, name=None):
    """reference geometric/math.py:29."""
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids,
                    _n_segments(segment_ids))


def segment_mean(data, segment_ids, name=None):
    """reference geometric/math.py:88."""
    n = _n_segments(segment_ids)

    def fn(xv, ids):
        ids = ids.astype(jnp.int32)
        tot = jax.ops.segment_sum(xv, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((xv.shape[0],), xv.dtype), ids,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (xv.ndim - 1))

    return run_op("segment_mean", fn, [data, segment_ids])


def segment_min(data, segment_ids, name=None):
    """reference geometric/math.py:149. Empty segments yield 0 (reference
    semantics)."""
    n = _n_segments(segment_ids)

    def fn(xv, ids):
        ids = ids.astype(jnp.int32)
        out = jax.ops.segment_min(xv, ids, num_segments=n)
        has = jax.ops.segment_sum(jnp.ones((xv.shape[0],), xv.dtype), ids,
                                  num_segments=n) > 0
        return jnp.where(has.reshape((-1,) + (1,) * (xv.ndim - 1)), out, 0)

    return run_op("segment_min", fn, [data, segment_ids])


def segment_max(data, segment_ids, name=None):
    """reference geometric/math.py:209."""
    n = _n_segments(segment_ids)

    def fn(xv, ids):
        ids = ids.astype(jnp.int32)
        out = jax.ops.segment_max(xv, ids, num_segments=n)
        has = jax.ops.segment_sum(jnp.ones((xv.shape[0],), xv.dtype), ids,
                                  num_segments=n) > 0
        return jnp.where(has.reshape((-1,) + (1,) * (xv.ndim - 1)), out, 0)

    return run_op("segment_max", fn, [data, segment_ids])


_SEG = {"sum": jax.ops.segment_sum, "mean": None,
        "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _reduce_messages(msg, dst, n, reduce_op):
    dst = dst.astype(jnp.int32)
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype), dst,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (msg.ndim - 1))
    out = _SEG[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("max", "min"):
        has = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype), dst,
                                  num_segments=n) > 0
        out = jnp.where(has.reshape((-1,) + (1,) * (msg.ndim - 1)), out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], reduce into dst slots (reference send_recv.py:55;
    kernel graph_send_recv_kernel.cu)."""
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    n = (_n_segments(dst_index) if out_size is None
         else _n_segments(dst_index, out_size))
    if out_size is None:
        n = max(n, int(x.shape[0]))

    def fn(xv, src, dst):
        msg = xv[src.astype(jnp.int32)]
        return _reduce_messages(msg, dst, n, reduce_op)

    return run_op("send_u_recv", fn, [x, src_index, dst_index])


_MSG_OPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Message = x[src] (op) y[edge]; reduce into dst (reference
    send_recv.py:210)."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"unknown reduce_op {reduce_op!r}")
    n = (_n_segments(dst_index) if out_size is None
         else _n_segments(dst_index, out_size))
    if out_size is None:
        n = max(n, int(x.shape[0]))

    def fn(xv, yv, src, dst):
        msg = _MSG_OPS[message_op](xv[src.astype(jnp.int32)], yv)
        return _reduce_messages(msg, dst, n, reduce_op)

    return run_op("send_ue_recv", fn, [x, y, src_index, dst_index])


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst] (reference send_recv.py:413)."""
    if message_op not in _MSG_OPS:
        raise ValueError(f"unknown message_op {message_op!r}")

    def fn(xv, yv, src, dst):
        return _MSG_OPS[message_op](xv[src.astype(jnp.int32)],
                                    yv[dst.astype(jnp.int32)])

    return run_op("send_uv", fn, [x, y, src_index, dst_index])


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact (x ∪ neighbors) into contiguous ids (reference
    reindex.py:34). Host-side: output size is data-dependent."""
    xv = _np(x).astype(np.int64)
    nb = _np(neighbors).astype(np.int64)
    cnt = _np(count).astype(np.int64)
    order = {}
    for v in xv.tolist():
        if v not in order:
            order[v] = len(order)
    for v in nb.tolist():
        if v not in order:
            order[v] = len(order)
    mapping = np.asarray(list(order.keys()), np.int64)
    reindex_src = np.asarray([order[v] for v in nb.tolist()], np.int64)
    reindex_dst = np.repeat(np.asarray(
        [order[v] for v in xv.tolist()], np.int64), cnt)
    return (to_tensor(reindex_src), to_tensor(reindex_dst),
            to_tensor(mapping))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Sample up to sample_size neighbors per input node from CSC
    (reference sampling/neighbors.py:30). Host-side (ragged output)."""
    r = _np(row).astype(np.int64)
    cp = _np(colptr).astype(np.int64)
    nodes = _np(input_nodes).astype(np.int64)
    rng = np.random.default_rng()
    out_nb, out_cnt, out_eid = [], [], []
    ev = _np(eids).astype(np.int64) if eids is not None else None
    for nd in nodes.tolist():
        beg, end = int(cp[nd]), int(cp[nd + 1])
        neigh = r[beg:end]
        eid = ev[beg:end] if ev is not None else None
        if 0 <= sample_size < len(neigh):
            sel = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[sel]
            if eid is not None:
                eid = eid[sel]
        out_nb.append(neigh)
        out_cnt.append(len(neigh))
        if eid is not None:
            out_eid.append(eid)
    nb = to_tensor(np.concatenate(out_nb)
                   if out_nb else np.empty(0, np.int64))
    cnt = to_tensor(np.asarray(out_cnt, np.int32))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return nb, cnt, to_tensor(np.concatenate(out_eid))
    return nb, cnt
