"""SOT graph-break capture: partial-graph compilation around data-dependent
Python control flow.

Reference analog: the reference SOT's opcode translator keeps compiling the
traceable subgraphs AROUND a BreakGraphError instead of abandoning the frame
(python/paddle/jit/sot/translate.py:97-106, sot/opcode_translator/). A frame
with one `if tensor > 0:` still runs mostly compiled there; a whole-frame
eager fallback loses ALL compilation for such frames.

TPU-native mechanism — trace-by-recording rather than bytecode translation:

1. RECORD: run the frame eagerly once with the op recorder + sync observer
   installed. Every run_op lands in the current segment; every concrete
   Tensor consumption by Python (`__bool__`/`__int__`/`__float__`/`item()`/
   `numpy()`/`tolist()` — the ways data steers control flow) closes the
   segment and records a GUARD (which value, what kind, what outcome).
2. COMPILE: each segment becomes ONE jitted replay of its ops. Externals
   (params, buffers) enter as runtime inputs, never baked constants, so
   weight/buffer updates are visible and autograd reaches params.
3. REPLAY: walk the guard tree — run segment 0 compiled, evaluate the guard
   on its concrete result, take the child matching the outcome, continue.
   An unseen outcome re-records a fresh path (guard-cached per split point).

Safety valves (fall back to plain eager — the always-correct behavior):
- a tensor created during recording by a path that bypasses run_op (nested
  jit, host-side mutation) cannot be replayed -> capture disables itself;
- array-valued guards larger than _MAX_GUARD_ELEMS;
- guard-tree explosion (continuous float guards taking a fresh branch every
  call) -> capture disables itself instead of re-recording forever.

RNG: PRNG-key tensors (framework.random.rng_tensor, tagged `_rng_key`) are
recorded as ("r", slot) entries and re-drawn from the global key on EVERY
replay — dropout masks vary per step exactly as in eager. Capture is keyed
per layer training mode by the to_static integration.

Values are named by deterministic value numbers (arg slot / op-output
ordinal / external), so paths recorded in different runs share a consistent
namespace for their common prefix. Segments execute through run_op, so the
eager autograd tape sees each one as a single fused op — a frame with one
dynamic branch runs as 2 compiled programs + 1 host sync instead of N eager
dispatches.
"""

from __future__ import annotations

import numpy as np

from ..framework import core as _core
from ..framework.core import (
    Tensor,
    run_op,
    set_op_recorder,
    set_sync_observer,
    tracing_guard,
)

__all__ = ["SOTCapture"]

_MAX_GUARD_ELEMS = 256   # array guards larger than this disable the capture
_MAX_CHILDREN = 16       # per-node branch outcomes before disabling
_MAX_WASTED_RECORDS = 16  # re-records with few replays => disable


class _SOTUnsupported(Exception):
    pass


class _Segment:
    """Ops between two graph breaks, entries referencing value numbers:
    ("a", i) arg slot, ("v", n) earlier op output, ("e", j) grad-requiring
    external, ("x", obj) constant external (buffer — passed as a live
    runtime input, NOT a baked closure constant), ("c", arr) constant."""

    def __init__(self, ops):
        self.ops = ops  # (fn, entries, out_vnums)
        need, produced, seen = [], [], set()
        xs, xseen = [], set()
        rs = []
        for _fn, entries, out_vnums in ops:
            for e in entries:
                if e[0] in ("a", "v", "e") and e[:2] not in seen \
                        and e[:2] not in produced:
                    need.append(e[:2])
                    seen.add(e[:2])
                elif e[0] == "x" and id(e[1]) not in xseen:
                    xs.append(e[1])
                    xseen.add(id(e[1]))
                elif e[0] == "r" and e[:2] not in seen:
                    rs.append(e[:2])
                    seen.add(e[:2])
            produced.extend(("v", n) for n in out_vnums)
        self.needed = [e for e in need if e not in produced]
        self.ext_objs = xs  # live tensors appended to the input list
        self.rng_entries = rs  # PRNG-key slots: fresh draw per run
        self.produced = produced
        needed = self.needed
        n_named = len(needed)
        x_index = {id(o): n_named + j for j, o in enumerate(xs)}
        r_index = {e: n_named + len(xs) + j for j, e in enumerate(rs)}

        def replay(*vals):
            local = dict(zip(needed, vals[:n_named]))

            def get(e):
                if e[0] == "c":
                    return e[1]
                if e[0] == "x":
                    return vals[x_index[id(e[1])]]
                if e[0] == "r":
                    return vals[r_index[e[:2]]]
                return local[e[:2]]

            with tracing_guard(True):
                for fn, entries, out_vnums in ops:
                    res = fn(*[get(e) for e in entries])
                    res_list = res if isinstance(res, tuple) else [res]
                    for n, val in zip(out_vnums, res_list):
                        local[("v", n)] = val
            return tuple(local[k] for k in produced)

        # ONE XLA program per segment — run_op's cache bypasses closures of
        # this shape, so jit here rather than relying on the dispatch cache
        import jax

        self._replay = jax.jit(replay)

    def run(self, env):
        args = [env[k] for k in self.needed] + self.ext_objs
        if self.rng_entries:
            from ..framework import random as rnd

            args += [rnd.next_key() for _ in self.rng_entries]
        produced = self.produced
        outs = run_op("sot_segment", self._replay, args,
                      n_outputs=len(produced) if len(produced) != 1 else None)
        outs = outs if isinstance(outs, (tuple, list)) else [outs]
        for k, t in zip(produced, outs):
            env[k] = t


class _Node:
    __slots__ = ("segment", "guard", "children", "result_spec", "_ext")

    def __init__(self):
        self.segment = None      # _Segment (None until recorded)
        self.guard = None        # (value_key_or_("x", obj), kind)
        self.children = {}       # outcome -> _Node
        self.result_spec = None  # terminal: pytree of value keys / constants


def _outcome(kind, value):
    if kind == "bool":
        return bool(value)
    if kind == "int":
        return int(value)
    if kind == "item":
        return np.asarray(value).item()
    if kind == "array":
        arr = np.asarray(value)
        if arr.size > _MAX_GUARD_ELEMS:
            raise _SOTUnsupported(
                f"array guard of {arr.size} elements")
        return (arr.shape, arr.tobytes())
    if isinstance(kind, tuple) and kind[0] == "cmp":
        # guard on the branch predicate, not the continuous value: a float
        # drawn from a training-evolving tensor repeats outcomes as long as
        # the comparison result does
        import operator

        _, op, other = kind
        if op == "truth":
            return bool(float(value))
        return bool(getattr(operator, op)(float(value), other))
    return float(value)


class _GuardedScalar(float):
    """What float(tensor)/tensor.item() returns inside a recording.

    Comparisons record their boolean outcome as the guard — the actual
    branch predicate (`if float(loss) > t:` guards on the bool, so replays
    survive the loss changing every step). Any other consumption
    (arithmetic, formatting, hashing) pins the exact value instead, which
    is always correct but re-records when the value drifts."""

    def __new__(cls, value, session, key):
        self = float.__new__(cls, value)
        self._session = session
        self._key = key
        return self

    def _cmp(self, op, other):
        import operator

        if not isinstance(other, (int, float, bool, np.number)):
            return NotImplemented
        if isinstance(other, _GuardedScalar):
            other._escape()
            other = float(other)
        out = bool(getattr(operator, op)(float(self), other))
        s = self._session
        if s["active"]:
            s["guard"](self._key, ("cmp", op, float(other)
                                   if not isinstance(other, bool) else other),
                       out)
        return out

    def __gt__(self, o):
        return self._cmp("gt", o)

    def __lt__(self, o):
        return self._cmp("lt", o)

    def __ge__(self, o):
        return self._cmp("ge", o)

    def __le__(self, o):
        return self._cmp("le", o)

    def __eq__(self, o):
        return self._cmp("eq", o)

    def __ne__(self, o):
        return self._cmp("ne", o)

    def __bool__(self):
        s = self._session
        out = float(self) != 0.0
        if s["active"]:
            s["guard"](self._key, ("cmp", "truth", None), out)
        return out

    def _escape(self):
        s = self._session
        if s["active"]:
            s["guard"](self._key, "float", float(self))

    def __hash__(self):
        self._escape()
        return float.__hash__(self)


def _escaping(name):
    base = getattr(float, name)

    def method(self, *a):
        self._escape()
        return base(self, *a)

    method.__name__ = name
    return method


for _m in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
           "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
           "__rfloordiv__", "__mod__", "__rmod__", "__pow__", "__rpow__",
           "__neg__", "__pos__", "__abs__", "__round__", "__str__",
           "__repr__", "__format__", "__int__", "__trunc__", "__floor__",
           "__ceil__"):
    setattr(_GuardedScalar, _m, _escaping(_m))


class SOTCapture:
    """Per-function graph-break capture with an (avals, outcomes) guard
    tree. stats: record_runs (eager recording passes), replay_runs (fully
    compiled executions), segments_run (compiled subgraphs executed)."""

    def __init__(self, fn):
        self.fn = fn
        self.roots = {}  # avals key -> _Node
        self.disabled = False  # permanent plain-eager fallback
        self.stats = {"record_runs": 0, "replay_runs": 0, "segments_run": 0}

    def _avals_key(self, args):
        key = []
        for a in args:
            if isinstance(a, Tensor):
                key.append(("t", tuple(a.shape), str(a._value.dtype)))
            elif isinstance(a, np.ndarray):
                # ndarray args enter recorded ops as baked constants, so the
                # trace is only valid for identical CONTENT — key by digest,
                # not repr (repr truncates large arrays)
                import hashlib

                key.append(("nd", a.shape, str(a.dtype),
                            hashlib.sha1(a.tobytes()).hexdigest()))
            else:
                key.append(("s", repr(a)))
        return tuple(key)

    def _disable(self):
        self.disabled = True
        self.roots.clear()

    # ------------------------------------------------------------------ #

    def _record(self, root, args):
        """Run fn eagerly from the start, recording/overwriting the path its
        guards take. Deterministic fn => a shared prefix re-records to
        identical segments, so sibling paths stay consistent."""
        self.stats["record_runs"] += 1
        if (self.stats["record_runs"] > _MAX_WASTED_RECORDS
                and self.stats["record_runs"]
                > 4 * max(self.stats["replay_runs"], 1)):
            # guards never repeat (continuous float guards): stop paying
            # recording overhead and run plain eager permanently
            self._disable()
            return self.fn(*args)
        names = {}  # id(tensor) -> value key
        keep = []   # keep recorded tensors alive so ids stay unique
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                names[id(a)] = ("a", i)
        counter = [0]
        rng_slots = [0]  # fresh-key slots handed out to ("r", j) entries
        seg_ops = []
        cur = {"node": root}
        ext = getattr(root, "_ext", None)
        if ext is None:
            ext = []
        root._ext = ext  # ("e", j) -> live tensor (grad-requiring external)
        start_ctr = _core._tensor_ctr

        def key_of(t):
            k = names.get(id(t))
            if k is not None:
                return k
            if not t.stop_gradient:
                # grad-requiring external (parameter): pass as a segment
                # INPUT so autograd reaches it and weight updates flow
                for j, o in enumerate(ext):
                    if o is t:
                        names[id(t)] = ("e", j)
                        return ("e", j)
                ext.append(t)
                k = ("e", len(ext) - 1)
                names[id(t)] = k
                return k
            if t._ctr >= start_ctr:
                if getattr(t, "_rng_key", False):
                    # PRNG key drawn during the frame (dropout etc.): a new
                    # slot whose replay value is a FRESH draw every run —
                    # never bake, or masks replay identically (the reference
                    # SOT re-seeds per step for the same reason)
                    k = ("r", rng_slots[0])
                    rng_slots[0] += 1
                    names[id(t)] = k
                    return k
                if t._host_const:
                    # materialized from host data during the frame (scalar
                    # promotion, np constant): a true frame constant
                    return ("c", np.asarray(t._value))
                # produced during this recording by a path run_op did not
                # see (nested jit): not replayable
                raise _SOTUnsupported(
                    "tensor created outside run_op during recording")
            return ("x", t)  # pre-existing external (buffer): live input

        def rec(name, fn, inputs, out):
            entries = []
            for i in inputs:
                if isinstance(i, Tensor):
                    entries.append(key_of(i))
                else:
                    entries.append(("c", np.asarray(i)))
            outs = out if isinstance(out, (list, tuple)) else [out]
            out_vnums = []
            for o in outs:
                if isinstance(o, Tensor):
                    n = counter[0]
                    counter[0] += 1
                    names[id(o)] = ("v", n)
                    keep.append(o)
                    out_vnums.append(n)
            seg_ops.append((fn, entries, out_vnums))
            if prev_rec is not None:  # chain an outer recorder (static)
                prev_rec(name, fn, inputs, out)

        session = {"active": True, "guard": None}

        def split_guard(key, kind, outc):
            node = cur["node"]
            node.segment = _Segment(list(seg_ops))
            seg_ops.clear()
            node.guard = (key, kind)
            child = node.children.get(outc)
            if child is None:
                if len(node.children) >= _MAX_CHILDREN:
                    raise _SOTUnsupported("guard outcomes never repeat")
                child = node.children[outc] = _Node()
            cur["node"] = child

        session["guard"] = split_guard

        def observe(kind, tensor):
            if kind == "item" and np.issubdtype(
                    np.asarray(tensor._value).dtype, np.floating):
                # defer the guard to the comparison on the returned scalar
                # (`if loss.item() > t:` guards on the bool). float(t) can't
                # get this treatment: CPython's float() coerces subclass
                # returns to plain float (dropping the guard hooks), so it
                # takes the exact-value guard below instead.
                return _GuardedScalar(float(np.asarray(tensor._value)),
                                      session, key_of(tensor))
            split_guard(key_of(tensor), kind, _outcome(kind, tensor._value))
            return None

        def spec_of(out):
            if isinstance(out, Tensor):
                # key_of raises _SOTUnsupported for unreplayable tensors
                # (nested-jit outputs) so the disable valve fires instead of
                # replays returning a stale record-time value
                k = key_of(out)
                if k[0] in ("x",):
                    return ("obj", k[1])  # pre-existing live object
                if k[0] == "c":
                    return ("const", out)
                return ("k", k)
            if isinstance(out, _GuardedScalar):
                # scalar derived from a recorded tensor: rebuild from its
                # source at replay, never bake the record-time value
                return ("scalar", out._key)
            if isinstance(out, (list, tuple)):
                return ("seq", type(out), [spec_of(o) for o in out])
            if isinstance(out, dict):
                return ("map", {kk: spec_of(v) for kk, v in out.items()})
            return ("const", out)

        prev_rec = _core._op_recorder
        set_op_recorder(rec)
        # set_* returns the previous BASE observer; reading the composed
        # _sync_observer slot here would capture (and later re-install as a
        # base) the add_*-chain dispatcher, double-firing chained observers
        prev_obs = set_sync_observer(observe)
        try:
            out = self.fn(*args)
        except _SOTUnsupported as _e:
            import os as _os

            if _os.environ.get("SOT_DEBUG"):
                import traceback as _tb

                _tb.print_exc()
            self._disable()
            set_op_recorder(prev_rec)
            set_sync_observer(prev_obs)
            return self.fn(*args)
        finally:
            session["active"] = False
            set_op_recorder(prev_rec)
            set_sync_observer(prev_obs)
        node = cur["node"]
        try:
            spec = spec_of(out)  # raises _SOTUnsupported for unreplayable
        except _SOTUnsupported:
            self._disable()
            return out
        node.segment = _Segment(list(seg_ops))
        node.guard = None
        node.result_spec = spec
        return out

    # ------------------------------------------------------------------ #

    @staticmethod
    def _build_result(spec, env):
        tag = spec[0]
        if tag == "k":
            return env[spec[1]]
        if tag == "scalar":
            k = spec[1]
            if k[0] == "c":
                return float(np.asarray(k[1]))
            src = k[1] if k[0] == "x" else env[k]
            return float(np.asarray(src._value))
        if tag == "obj":
            return spec[1]
        if tag == "seq":
            return spec[1](SOTCapture._build_result(s, env) for s in spec[2])
        if tag == "map":
            return {k: SOTCapture._build_result(v, env)
                    for k, v in spec[1].items()}
        return spec[1]

    def __call__(self, *args):
        if self.disabled:
            return self.fn(*args)
        key = self._avals_key(args)
        root = self.roots.get(key)
        if root is None:
            root = self.roots[key] = _Node()
            return self._record(root, args)

        env = {}
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                env[("a", i)] = a
        for j, o in enumerate(getattr(root, "_ext", [])):
            env[("e", j)] = o  # live object: current param value + grad path
        node = root
        segs = 0
        while True:
            if node.segment is None:
                return self._record(root, args)
            node.segment.run(env)
            segs += 1
            if node.guard is None:
                self.stats["replay_runs"] += 1
                self.stats["segments_run"] += segs
                return self._build_result(node.result_spec, env)
            gkey, kind = node.guard
            if gkey[0] == "x":
                gval = gkey[1]._value  # live external
            elif gkey[0] == "c":
                gval = gkey[1]  # baked host constant: outcome is fixed
            else:
                gval = env[gkey]._value
            try:
                child = node.children.get(_outcome(kind, gval))
            except _SOTUnsupported:
                self._disable()
                return self.fn(*args)
            if child is None:
                # unseen branch outcome: record a fresh path
                return self._record(root, args)
            node = child
