"""jit / to_static: the traced execution path.

Reference analog: paddle.jit.to_static (python/paddle/jit/api.py:197) backed by
AST transforms + SOT bytecode tracing (python/paddle/jit/sot/translate.py) that
build a static Program run by the PirInterpreter. On TPU the entire pipeline
collapses into jax.jit: user Layers execute once under a tracer (module-state
swap — parameters temporarily wrap tracers), producing one XLA program with
guard-based retrace on new input signatures, which is exactly the SOT
guard-cache contract.

Two entry points:
- to_static(fn): trace-and-guard jit of any Tensor->Tensor callable (params
  captured as constants; inference / frozen-weight use).
- TrainStep(model, loss, optimizer): the whole train step (fwd, bwd, optimizer
  update, buffer updates, AMP) as ONE compiled+donated XLA program — replacing
  the reference's per-op dispatch AND its fused optimizer kernels.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd
from ..framework.core import Parameter, Tensor, no_grad, to_tensor, tracing_guard
from ..nn.layer.layers import Layer

__all__ = ["to_static", "TrainStep", "functional_call", "save", "load", "not_to_static", "ignore_module", "InputSpec", "TranslatedLayer"]


def _unwrap_pytree(obj):
    if isinstance(obj, Tensor):
        return obj._value
    if isinstance(obj, (list, tuple)):
        t = [_unwrap_pytree(o) for o in obj]
        return type(obj)(t) if not isinstance(obj, tuple) else tuple(t)
    if isinstance(obj, dict):
        return {k: _unwrap_pytree(v) for k, v in obj.items()}
    return obj


def _wrap_pytree(obj):
    if isinstance(obj, (jax.Array, np.ndarray)):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        t = [_wrap_pytree(o) for o in obj]
        return type(obj)(t) if not isinstance(obj, tuple) else tuple(t)
    if isinstance(obj, dict):
        return {k: _wrap_pytree(v) for k, v in obj.items()}
    return obj


class _ModuleState:
    """Swap a Layer tree's param/buffer values for traced values and restore."""

    def __init__(self, layer: Layer):
        self.layer = layer
        self.params = dict(layer.named_parameters())
        self.buffers = dict(layer.named_buffers())

    def values(self):
        return (
            {k: p._value for k, p in self.params.items()},
            {k: b._value for k, b in self.buffers.items()},
        )

    def swap_in(self, param_vals, buffer_vals):
        saved_p = {k: p._value for k, p in self.params.items()}
        saved_b = {k: b._value for k, b in self.buffers.items()}
        for k, v in (param_vals or {}).items():
            self.params[k]._value = v
        for k, v in (buffer_vals or {}).items():
            self.buffers[k]._value = v
        return saved_p, saved_b

    def read_buffers(self):
        return {k: b._value for k, b in self.buffers.items()}

    def restore(self, saved):
        saved_p, saved_b = saved
        for k, v in saved_p.items():
            self.params[k]._value = v
        for k, v in saved_b.items():
            self.buffers[k]._value = v


def functional_call(layer: Layer, param_vals, buffer_vals, args, kwargs=None, train=None, rng_key=None):
    """Run layer(*args) with the given raw param/buffer values, purely.

    Returns (outputs_raw, new_buffer_vals). Works under jax tracing: the
    module-state swap makes user Layer code (written against the eager API)
    execute as a pure jax function — the TPU-native replacement for the
    reference's dy2static AST rewriting.
    """
    kwargs = kwargs or {}
    state = _ModuleState(layer)
    saved = state.swap_in(param_vals, buffer_vals)
    prev_training = layer.training
    if train is not None:
        layer.train() if train else layer.eval()
    saved_rng = rnd.get_rng_state()
    if rng_key is not None:
        rnd.set_rng_state((rng_key,))
    try:
        with tracing_guard(True):
            wrapped_args = [_wrap_pytree(a) if not isinstance(a, Tensor) else a for a in args]
            out = layer(*wrapped_args, **kwargs)
        new_bufs = state.read_buffers()
        return _unwrap_pytree(out), new_bufs
    finally:
        state.restore(saved)
        rnd.set_rng_state(saved_rng)
        if train is not None:
            layer.train() if prev_training else layer.eval()


def _is_trace_ineligible(e) -> bool:
    """Errors meaning 'this Python frame cannot be traced' — data-dependent
    control flow / shapes (the reference SOT's ineligible-frame set,
    python/paddle/jit/sot/translate.py BreakGraphError)."""
    import jax.errors as jerr

    return isinstance(e, (jerr.TracerBoolConversionError,
                          jerr.ConcretizationTypeError,
                          jerr.TracerArrayConversionError,
                          jerr.TracerIntegerConversionError,
                          jerr.NonConcreteBooleanIndexError))


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """Decorator/wrapper: jit a Tensor-level callable or a Layer's forward.

    Shape-signature guarding comes from jax.jit's tracing cache — a new input
    (shape, dtype) signature triggers a retrace, matching the reference SOT
    guard semantics (python/paddle/jit/sot/translate.py:97-106). Frames the
    tracer cannot swallow (data-dependent Python control flow, concretized
    shapes) fall back to SOT GRAPH-BREAK CAPTURE (jit/sot.py): the frame is
    re-run once eagerly while recording, split at the concrete-value sync
    points, and thereafter executes as compiled subgraphs around the breaks
    — the reference SOT's partial-graph behavior (translate.py
    BreakGraphError path) rather than losing all compilation.
    """
    if function is None:
        return lambda f: to_static(f, input_spec=input_spec)

    if isinstance(function, Layer):
        layer = function
        orig_forward = layer.forward

        compiled = _make_layer_jit(layer, orig_forward)
        layer.forward = compiled
        layer._to_static_origin = orig_forward
        return layer

    fn = function

    @jax.jit
    def traced(raw_args):
        with tracing_guard(True):
            args = _wrap_pytree(raw_args)
            out = fn(*args)
        return _unwrap_pytree(out)

    fell_back = [False]
    sot = [None]

    @functools.wraps(fn)
    def wrapper(*args):
        if fell_back[0]:
            return sot[0](*args)
        raw = _unwrap_pytree(list(args))
        try:
            out = traced(raw)
        except Exception as e:
            if not _is_trace_ineligible(e):
                raise
            # graph-break capture: compiled subgraphs around the dynamic
            # control flow instead of a permanent whole-frame eager fallback
            from .sot import SOTCapture

            fell_back[0] = True
            sot[0] = SOTCapture(fn)
            return sot[0](*args)
        return _wrap_pytree(out)

    wrapper._original_fn = fn
    wrapper._sot_fallen_back = fell_back
    wrapper._sot_capture = sot
    return wrapper


def _make_layer_jit(layer, orig_forward):
    """jit a Layer's forward: params/buffers become traced args so weight
    updates don't trigger recompiles; buffers update functionally."""
    jit_cache = {}
    fell_back = [False]
    sot = [{}]  # training-mode -> SOTCapture

    def forward(*args, **kwargs):
        if kwargs:
            # kwargs would be baked into the trace as constants
            return orig_forward(*args, **kwargs)
        if fell_back[0]:
            # one capture per training mode: recorded segments bake the
            # train/eval branch (dropout, BN stat source)
            from .sot import SOTCapture

            mode = bool(layer.training)
            if sot[0].get(mode) is None:
                sot[0][mode] = SOTCapture(orig_forward)
            return sot[0][mode](*args)
        state = _ModuleState(layer)
        p_vals, b_vals = state.values()
        training = layer.training

        key = "train" if training else "eval"
        if key not in jit_cache:
            @functools.partial(jax.jit, static_argnums=())
            def step(p, b, rng, raw_args):
                saved = state.swap_in(p, b)
                saved_rng = rnd.get_rng_state()
                rnd.set_rng_state((rng,))
                try:
                    with tracing_guard(True):
                        out = orig_forward(*_wrap_pytree(raw_args), **kwargs)
                    return _unwrap_pytree(out), state.read_buffers()
                finally:
                    state.restore(saved)
                    rnd.set_rng_state(saved_rng)

            jit_cache[key] = step
        raw_args = _unwrap_pytree(list(args))
        try:
            out, new_bufs = jit_cache[key](p_vals, b_vals, rnd.next_key(), raw_args)
        except Exception as e:
            if not _is_trace_ineligible(e):
                raise
            from .sot import SOTCapture

            fell_back[0] = True
            mode = bool(layer.training)
            sot[0][mode] = SOTCapture(orig_forward)
            return sot[0][mode](*args)
        for k, v in new_bufs.items():
            state.buffers[k]._value = v
        return _wrap_pytree(out)

    forward._sot_fallen_back = fell_back
    forward._sot_capture = sot
    return forward


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    return None


class TrainStep:
    """One compiled train step: loss, grads, clip, optimizer update, buffer
    (BN stat) updates — fused into a single donated XLA program.

    Replaces, in one object: the reference's dygraph per-op dispatch, AMP
    autocast pass, ClipGradByGlobalNorm kernel, and the fused/multi_tensor
    optimizer kernels (paddle/phi/kernels/fusion/gpu/fused_adam_kernel.cu).

    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)            # all device-side
        step.sync_weights()          # write back into model Tensors
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, amp_level=None, amp_dtype="bfloat16", donate=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        self._state = _ModuleState(model)
        p_vals, b_vals = self._state.values()
        self.params = p_vals
        self.buffers = b_vals
        self.opt_states = {k: optimizer.init_state(v) for k, v in p_vals.items()}
        self._step = 0
        self._compiled = None
        self._donate = donate

    def _build(self):
        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        state = self._state
        amp_level, amp_dtype = self.amp_level, self.amp_dtype
        grad_clip = opt._grad_clip
        wd = opt._decay_coeff()
        # per-param regularizers (ParamAttr(regularizer=...)): applied to
        # the grads inside the compiled program, and they REPLACE the
        # optimizer-level weight_decay for their params (same semantics as
        # the eager Optimizer.step / reference append_regularization_ops)
        reg_specs = {}
        for _k, _prm in state.params.items():
            _r = getattr(_prm, "regularizer", None)
            if _r is not None:
                from ..regularizer import L1Decay

                reg_specs[_k] = ("l1" if isinstance(_r, L1Decay) else "l2",
                                 float(_r._coeff))

        # models that must see the loss inside their compiled schedule (1F1B
        # pipelining: the last stage seeds its own backward) expose
        # forward_loss(inputs..., labels..., criterion) — reference analog:
        # PipelineParallel owns the loss in train_batch (pipeline_parallel
        # .py:940) rather than the user loop
        fused_loss = (getattr(model, "forward_loss", None)
                      if getattr(model, "pp_schedule", None) == "1f1b" else None)

        def compute_loss(p, b, rng, batch):
            # grad-overlap hook: DistributedTrainStep tags params with
            # custom-VJP bucket identities whose backward applies the
            # reduce-scatter sharding constraint where the grad is PRODUCED
            # (per-layer, against remaining backward compute) instead of at
            # the step-end consumption site
            p = self._tag_grad_buckets(p)
            saved = state.swap_in(p, b)
            saved_rng = rnd.get_rng_state()
            rnd.set_rng_state((rng,))
            try:
                with tracing_guard(True):
                    ctx = _amp_ctx(amp_level, amp_dtype)
                    with ctx:
                        if fused_loss is not None:
                            loss = fused_loss(
                                *_wrap_pytree(list(batch["inputs"])),
                                *_wrap_pytree(list(batch["labels"])),
                                loss_fn)
                        else:
                            out = model(*_wrap_pytree(list(batch["inputs"])))
                            outs = out if isinstance(out, (list, tuple)) else [out]
                            loss = loss_fn(*outs, *_wrap_pytree(list(batch["labels"])))
                return loss._value.astype(jnp.float32), state.read_buffers()
            finally:
                state.restore(saved)
                rnd.set_rng_state(saved_rng)

        def train_step(p, opt_states, b, rng, step_i, lr, batch):
            # offload streaming: host-resident optimizer states enter the
            # program through in-program device_puts (overlappable h2d
            # copies scheduled by XLA) instead of a host-side move barrier
            opt_states = self._fetch_opt_states(opt_states)
            (loss, new_b), grads = jax.value_and_grad(compute_loss, has_aux=True)(p, b, rng, batch)
            if reg_specs:
                grads = dict(grads)
                for k, (kind, coeff) in reg_specs.items():
                    gk = grads[k].astype(jnp.float32)
                    pk = p[k].astype(jnp.float32)
                    add = coeff * (jnp.sign(pk) if kind == "l1" else pk)
                    grads[k] = (gk + add).astype(grads[k].dtype)
            # global-norm clip (fused into the same program)
            if grad_clip is not None:
                clip_norm = getattr(grad_clip, "clip_norm", None)
                if clip_norm is not None:
                    gnorm = jnp.sqrt(
                        sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree.leaves(grads))
                    )
                    scale = clip_norm / jnp.maximum(gnorm, clip_norm)
                    grads = jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)
            new_p, new_states = {}, {}
            for k in p:
                ctx = {"step": step_i,
                       "weight_decay": 0.0 if k in reg_specs else wd}
                st = opt_states[k]
                master = st.get("master")
                pv = master if master is not None else p[k]
                # sharding-stage hooks (ZeRO-2/3): reduce-scatter the grad to
                # its owner shard and compute the update sharded, then
                # all-gather the fresh params (DistributedTrainStep overrides)
                gv = self._shard_grad(k, grads[k].astype(pv.dtype))
                pv = self._shard_param_for_update(k, pv)
                rule_state = {kk: vv for kk, vv in st.items() if kk != "master"}
                np_, ns_ = opt.update(pv, gv, rule_state, lr, ctx)
                if master is not None:
                    ns_ = dict(ns_)
                    ns_["master"] = np_
                    np_ = np_.astype(p[k].dtype)
                new_p[k] = self._restore_param(k, np_)
                # per-param d2h emission point: under offload streaming the
                # fresh states head back to host memory HERE, pipelined
                # against the remaining params' updates
                new_states[k] = self._emit_opt_state(k, ns_)
            return loss, new_p, new_states, new_b

        donate = (0, 1, 2) if self._donate else ()
        out_sh = self._train_out_shardings()
        kw = {"out_shardings": out_sh} if out_sh is not None else {}
        self._compiled = jax.jit(train_step, donate_argnums=donate, **kw)

        def eval_step(p, b, rng, batch):
            loss, _ = compute_loss(p, b, rng, batch)
            return loss

        self._compiled_eval = jax.jit(eval_step)

    # sharding-stage hooks; identity here, overridden by DistributedTrainStep
    def _shard_grad(self, name, g):
        return g

    def _shard_param_for_update(self, name, pv):
        return pv

    def _restore_param(self, name, np_):
        return np_

    # comm-overlap hooks; identity here, overridden by DistributedTrainStep
    def _tag_grad_buckets(self, p):
        return p

    def _fetch_opt_states(self, opt_states):
        return opt_states

    def _emit_opt_state(self, name, st):
        return st

    def _post_dispatch(self):
        """Runs inside the step's compute span, right after the compiled
        call returns (the device is still executing asynchronously) — the
        overlap point for host-issued follow-up transfers."""

    def _train_out_shardings(self):
        """Optional out_shardings for (loss, new_p, new_states, new_b) —
        used by the offload path to keep optimizer states host-resident."""
        return None

    def __call__(self, inputs, labels):
        if self._compiled is None:
            # multi-precision: seed master copies
            if self.optimizer._multi_precision:
                for k, v in self.params.items():
                    if v.dtype in (jnp.bfloat16, jnp.float16):
                        self.opt_states[k]["master"] = v.astype(jnp.float32)
            self._build()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        self._step += 1
        batch = {
            "inputs": [_unwrap_pytree(i if isinstance(i, Tensor) else to_tensor(i)) for i in inputs],
            "labels": [_unwrap_pytree(l if isinstance(l, Tensor) else to_tensor(l)) for l in labels],
        }
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_i = jnp.asarray(self._step, jnp.int32)
        from ..observability import spans as _obs_spans

        # kind="compute": the step's compute interval for the overlap
        # accounting (overlap_stats). The span covers the async dispatch and
        # _post_dispatch — transfers issued there run while the device is
        # still executing this step's program.
        with _obs_spans.span("train_step/compiled", kind="compute"):
            loss, self.params, self.opt_states, self.buffers = self._compiled(
                self.params, self.opt_states, self.buffers, rnd.next_key(), step_i, lr, batch
            )
            self._post_dispatch()
        return Tensor(loss)

    def evaluate(self, inputs, labels):
        if self._compiled is None:
            self._build()
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        was_training = self.model.training
        self.model.eval()
        try:
            batch = {
                "inputs": [_unwrap_pytree(i if isinstance(i, Tensor) else to_tensor(i)) for i in inputs],
                "labels": [_unwrap_pytree(l if isinstance(l, Tensor) else to_tensor(l)) for l in labels],
            }
            loss = self._compiled_eval(self.params, self.buffers, rnd.next_key(), batch)
            return Tensor(loss)
        finally:
            if was_training:
                self.model.train()

    @no_grad()
    def sync_weights(self):
        """Write device-side params/buffers back into the model's Tensors."""
        for k, v in self.params.items():
            self._state.params[k]._value = v
        for k, v in self.buffers.items():
            self._state.buffers[k]._value = v

    @no_grad()
    def sync_optimizer(self):
        """Write device-side optimizer state back into the Optimizer so
        optimizer.state_dict() reflects training (checkpoint correctness)."""
        for k, st in self.opt_states.items():
            param = self._state.params[k]
            self.optimizer._states[id(param)] = dict(st)
        self.optimizer._step_count = self._step


def _amp_ctx(level, dtype):
    import contextlib

    if level in ("O1", "O2"):
        from ..amp import auto_cast

        return auto_cast(True, level=level, dtype=dtype)
    return contextlib.nullcontext()


class InputSpec:
    """Shape/dtype spec for traced export (reference: paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def _sds(self, scope=None):
        from ..framework import dtype as dtype_mod

        dt = jnp.dtype(dtype_mod.convert_dtype(self.dtype))
        if any(d is None for d in self.shape):
            # dynamic dims (the reference's None batch dims) -> jax.export
            # symbolic shapes; one shared scope per save() call
            from jax import export as jexport

            names = iter("abcdefghijklmnop")
            dims = ",".join(str(d) if d is not None else next(names)
                            for d in self.shape)
            shape = jexport.symbolic_shape(dims, scope=scope)
            return jax.ShapeDtypeStruct(shape, dt)
        return jax.ShapeDtypeStruct(self.shape, dt)


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save (reference: python/paddle/jit/api.py) — persist weights
    AND, when input_spec is given, the traced program itself: the forward is
    traced to StableHLO via jax.export (params captured as constants) and
    serialized to `path`.pdmodel — the analog of the reference's saved
    Program/PIR artifact. Weights always go to `path`.pdparams."""
    from ..framework.io import save as fsave

    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    state = layer.state_dict()
    fsave({"state_dict": state, "class": type(layer).__qualname__}, path + ".pdparams")
    if input_spec is not None:
        from jax import export as jexport

        params = {k: p._value for k, p in layer.named_parameters()}
        buffers = {k: b._value for k, b in layer.named_buffers()}

        def fwd(*xs):
            out, _ = functional_call(layer, params, buffers,
                                     [Tensor(x) for x in xs], train=False)
            return out

        from jax import export as _jexp

        scope = _jexp.SymbolicScope()
        sds = [s._sds(scope) if isinstance(s, InputSpec) else
               jax.ShapeDtypeStruct(tuple(s.shape), jnp.dtype(s.dtype))
               for s in input_spec]
        # the serving artifact is a SINGLE-device program: a lingering
        # global training mesh (DistributedTrainStep sets one) must not
        # leak into the export, or the saved model demands that device
        # count at load time (jax.export records nr_devices)
        from ..distributed import env as _dist_env

        prev_mesh = _dist_env.get_global_mesh()
        _dist_env.set_global_mesh(None)
        try:
            exported = jexport.export(jax.jit(fwd))(*sds)
        finally:
            _dist_env.set_global_mesh(prev_mesh)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())


class TranslatedLayer(Layer):
    """A loaded saved-program (reference: TranslatedLayer from paddle.jit.load
    running a deserialized Program on the executor) — here a deserialized
    StableHLO program invoked through jax.export."""

    def __init__(self, exported, state=None):
        super().__init__()
        self._exported = exported
        self._state = state or {}

    def forward(self, *args):
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._exported.call(*raw)
        return _wrap_pytree(out)

    def state_dict(self, *a, **kw):
        return dict(self._state)

    @property
    def input_shapes(self):
        return [tuple(a.shape) for a in self._exported.in_avals]


def load(path, **configs):
    """paddle.jit.load — with a .pdmodel program file returns a runnable
    TranslatedLayer; otherwise returns the saved dict (weights-only load)."""
    import os

    from jax import export as jexport

    from ..framework.io import load as fload

    payload = fload(path + ".pdparams")
    if os.path.exists(path + ".pdmodel"):
        with open(path + ".pdmodel", "rb") as f:
            exported = jexport.deserialize(f.read())
        return TranslatedLayer(exported, payload.get("state_dict"))
    return payload
