"""Shared-memory batch channel for process-mode DataLoader workers.

Reference: python/paddle/io/dataloader/worker.py + the C++ shared-memory
transport (data_feed.cc) — batches cross the worker→trainer process boundary
through shared memory, not pipe pickling.

Backed by the native ring (native/shm_ring.cc): variable-size records in one
POSIX shm segment with process-shared mutex/condvars. One ring per worker;
the parent pops rings round-robin so per-ring FIFO order gives global batch
order without index headers.
"""

from __future__ import annotations

import ctypes
import itertools
import os
import pickle
import uuid

from ..framework import native

__all__ = ["ShmRing"]

# Monotonic per-process sequence for ring names. id(object()) was reused
# across consecutive calls, colliding all workers onto one segment.
_ring_seq = itertools.count()


class ShmRing:
    def __init__(self, handle, lib, name, owner):
        self._h = handle
        self._lib = lib
        self._name = name
        self._owner = owner

    @classmethod
    def create(cls, capacity=64 << 20, name=None):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable — shm ring needs "
                               "native/libpaddle_tpu_native.so")
        name = name or (
            f"/pdtpu_ring_{os.getpid()}_{next(_ring_seq)}_{uuid.uuid4().hex[:8]}"
        )
        h = lib.shm_ring_create(name.encode(), int(capacity))
        if not h:
            raise RuntimeError(f"shm_ring_create({name}) failed")
        return cls(h, lib, name, owner=True)

    @classmethod
    def attach(cls, name):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        h = lib.shm_ring_attach(name.encode())
        if not h:
            raise RuntimeError(f"shm_ring_attach({name}) failed")
        return cls(h, lib, name, owner=False)

    @property
    def name(self):
        return self._name

    def push(self, obj):
        """Blocking push of one pickled record. Raises on closed ring."""
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.shm_ring_push(self._h, payload, len(payload))
        if rc == -2:
            raise ValueError(
                f"record of {len(payload)} bytes exceeds ring capacity; "
                "raise DataLoader shm_capacity")
        if rc != 0:
            raise EOFError("ring closed")

    def pop(self):
        """Blocking pop; returns the object or raises EOFError when closed+empty."""
        n = self._lib.shm_ring_peek(self._h)
        cap = max(n, 1 << 16)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.shm_ring_pop(self._h, buf, cap)
            if n == -1:
                raise EOFError("ring closed")
            if n == -2:
                cap *= 4
                continue
            return pickle.loads(buf.raw[:n])

    def close(self):
        if self._h:
            self._lib.shm_ring_close(self._h)

    def destroy(self):
        if self._h:
            self._lib.shm_ring_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            if self._owner:
                self.destroy()
        except Exception:
            pass
