"""Data loading (reference: python/paddle/io/ — Dataset/DataLoader
reader.py:262, samplers, multi-process workers io/dataloader/worker.py).

TPU-native stance: the DataLoader is a host-side prefetch pipeline. Instead of
the reference's multi-process shared-memory workers feeding a CUDA stream, we
use a thread pool (NumPy collation releases the GIL) plus a bounded prefetch
queue so host batch prep overlaps device steps; batches are numpy until they
cross into jax at dispatch.
"""

from __future__ import annotations

import itertools
import queue
import threading
from typing import Iterable

import numpy as np

from ..framework.core import Tensor, to_tensor

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "ComposeDataset",
    "ChainDataset",
    "Subset",
    "ConcatDataset",
    "random_split",
    "Sampler",
    "SequenceSampler",
    "RandomSampler",
    "WeightedRandomSampler",
    "BatchSampler",
    "DistributedBatchSampler",
    "DataLoader",
    "default_collate_fn",
    "get_worker_info",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if ds == 0 else int(self.cum[ds - 1])
        return self.datasets[ds][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(np.floor(n * f)) for f in lengths]
        lengths[-1] += n - sum(lengths)
    total = sum(lengths)
    perm = np.random.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(p), self.num_samples, replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharded batches (reference: python/paddle/io/dataloader/
    batch_sampler.py DistributedBatchSampler). On the single-controller TPU
    runtime this shards by data-parallel rank for multi-host input."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank

            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = max(num_replicas, 1)
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - len(indices))]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


class _WorkerError:
    def __init__(self, exc):
        self.exc = exc


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays, then Tensors."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s.numpy()) for s in batch]))
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.number)):
        return to_tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    return batch


class DataLoader:
    """reference: paddle.io.DataLoader (python/paddle/io/reader.py:262).

    num_workers>0 uses a thread pool + bounded prefetch queue (host pipeline
    overlapping the device step) rather than fork+shared-memory — there's no
    CUDA context to protect on TPU and numpy collation drops the GIL.
    """

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False,
                 worker_mode="thread", shm_capacity=64 << 20):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        # "process": forked workers + native shared-memory rings (the
        # reference's worker.py/data_feed transport); "thread": GIL-dropping
        # numpy pipeline, the TPU default
        self.worker_mode = worker_mode
        self.shm_capacity = shm_capacity
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _iter_iterable(self):
        batch = []
        for item in self.dataset:
            batch.append(item)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])
            return
        if self.worker_mode == "process":
            yield from self._iter_processes()
            return
        yield from self._iter_threaded()

    def _iter_threaded(self):
        idx_batches = list(self.batch_sampler)
        ready = queue.Queue(maxsize=max(2, self.num_workers * self.prefetch_factor))
        task_q = queue.Queue()
        for i, b in enumerate(idx_batches):
            task_q.put((i, b))

        results: dict[int, object] = {}
        next_emit = 0

        def worker(wid):
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            _worker_info.info = type("WorkerInfo", (), {"id": wid, "num_workers": self.num_workers, "dataset": self.dataset})()
            while True:
                try:
                    i, b = task_q.get_nowait()
                except queue.Empty:
                    return
                try:
                    data = self.collate_fn([self.dataset[j] for j in b])
                    ready.put((i, data))
                except BaseException as e:  # propagate to the consumer
                    ready.put((i, _WorkerError(e)))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True) for w in range(self.num_workers)]
        for t in threads:
            t.start()
        emitted = 0
        while emitted < len(idx_batches):
            i, data = ready.get()
            if isinstance(data, _WorkerError):
                raise data.exc
            results[i] = data
            while next_emit in results:
                yield results.pop(next_emit)
                next_emit += 1
                emitted += 1
        for t in threads:
            t.join(timeout=1)

    def _iter_processes(self):
        """Forked workers pushing collated batches through native shm rings
        (io/shm_channel.py; reference: io/dataloader/worker.py). Worker w
        handles batches w, w+W, ... so per-ring FIFO = global batch order."""
        import multiprocessing as mp
        import os as _os

        from .shm_channel import ShmRing

        idx_batches = list(self.batch_sampler)
        W = self.num_workers
        rings = [ShmRing.create(self.shm_capacity) for _ in range(W)]
        ctx = mp.get_context("fork")

        def worker(wid, ring_name, batches):
            ring = ShmRing.attach(ring_name)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            _worker_info.info = type("WorkerInfo", (), {
                "id": wid, "num_workers": W, "dataset": self.dataset})()
            try:
                for b in batches:
                    try:
                        data = self.collate_fn([self.dataset[j] for j in b])
                        ring.push(("ok", data))
                    except BaseException as e:
                        ring.push(("err", repr(e)))
                        return
            except EOFError:
                pass
            _os._exit(0)

        procs = []
        for w in range(W):
            batches = idx_batches[w::W]
            p = ctx.Process(target=worker, args=(w, rings[w].name, batches),
                            daemon=True)
            p.start()
            procs.append(p)
        try:
            for i in range(len(idx_batches)):
                tag, data = rings[i % W].pop()
                if tag == "err":
                    raise RuntimeError(f"DataLoader worker failed: {data}")
                yield data
        finally:
            for r in rings:
                r.close()
            for p in procs:
                p.join(timeout=2)
                if p.is_alive():
                    p.terminate()
            for r in rings:
                r.destroy()
