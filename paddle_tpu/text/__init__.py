"""Text utilities (reference: python/paddle/text/ — viterbi_decode.py
ViterbiDecoder/viterbi_decode; the download zoo is out of scope in a
zero-egress build, but LOCAL-file dataset loaders for the same corpora
live in paddle_tpu.text.datasets).

TPU formulation: Viterbi is a lax.scan over time with a [B, T, T] max-plus
step — static shapes, no host loop (the reference's viterbi_decode_kernel
is a CUDA time loop)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from ..framework.core import Tensor, run_op, to_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]

from . import datasets  # noqa: E402


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """reference: paddle.text.viterbi_decode — returns (scores, paths).

    potentials: [B, T, N] emission scores; transition_params: [N, N];
    lengths: [B] valid steps (default: full length). With
    include_bos_eos_tag, row N-2 is BOS and N-1 is EOS like the reference.
    """
    pot = potentials if isinstance(potentials, Tensor) else to_tensor(potentials)
    trans = (transition_params if isinstance(transition_params, Tensor)
             else to_tensor(transition_params))
    B, T, N = pot.shape
    if lengths is None:
        import numpy as np

        lengths = to_tensor(np.full((B,), T, np.int64))
    lens = lengths if isinstance(lengths, Tensor) else to_tensor(lengths)

    def fn(p, tr, ln):
        ln = ln.astype(jnp.int32)
        if include_bos_eos_tag:
            # start from BOS row, end with EOS column
            alpha0 = p[:, 0] + tr[N - 2][None, :]
        else:
            alpha0 = p[:, 0]

        def step(carry, inp):
            alpha, t = carry
            emit = inp  # [B, N]
            scores = alpha[:, :, None] + tr[None]  # [B, from, to]
            best_prev = jnp.argmax(scores, axis=1)  # [B, N]
            alpha_new = jnp.max(scores, axis=1) + emit
            # freeze lanes past their length
            active = (t < ln)[:, None]
            alpha_new = jnp.where(active, alpha_new, alpha)
            best_prev = jnp.where(active, best_prev, jnp.arange(N)[None])
            return (alpha_new, t + 1), best_prev

        (alpha, _), backptrs = jax.lax.scan(
            step, (alpha0, jnp.ones((), jnp.int32)),
            jnp.swapaxes(p[:, 1:], 0, 1))  # [T-1, B, N]
        if include_bos_eos_tag:
            alpha = alpha + tr[:, N - 1][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)  # [B]

        def back(carry, bp):
            # processing index i (reverse): carry holds tag_{i+1}; emit it,
            # step to tag_i = backptrs[i][tag_{i+1}]
            tag, t = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            tag_new = jnp.where(t < ln, prev, tag)
            return (tag_new, t - 1), tag

        (tag0, _), path_tail = jax.lax.scan(
            back, (last, jnp.full((), T - 1, jnp.int32)), backptrs,
            reverse=True)  # path_tail[i] = tag_{i+1}; final carry = tag_0
        paths = jnp.concatenate([tag0[None], path_tail], axis=0)  # [T, B]
        return scores, jnp.swapaxes(paths, 0, 1).astype(jnp.int32)

    return run_op("viterbi_decode", fn, [pot, trans, lens], n_outputs=2)


class ViterbiDecoder(nn.Layer):
    """reference: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = (transitions if isinstance(transitions, Tensor)
                            else to_tensor(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
