"""Local-file text dataset loaders (reference: python/paddle/text/datasets/
imdb.py:39, imikolov.py, conll05.py, uci_housing.py, wmt14.py).

Zero-egress design: the reference classes download + cache corpora; here
each class reads the SAME on-disk formats from user-supplied paths (the
post-download layout), plus a synthetic mode for pipeline tests. Loading is
host-side NumPy — datasets feed the shm-ring DataLoader workers
(io/__init__.py), never the device."""

from __future__ import annotations

import gzip
import os
import re
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "WMT14"]


def _open_maybe_gz(path, mode="rb"):
    return gzip.open(path, mode) if str(path).endswith(".gz") else \
        open(path, mode)


class Imdb(Dataset):
    """IMDB sentiment (reference text/datasets/imdb.py:39). Reads the
    aclImdb tar layout (`aclImdb/{train,test}/{pos,neg}/*.txt`) from
    `data_file`; builds the vocabulary from the train split with `cutoff`
    frequency like the reference."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if download or data_file is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_file "
                "pointing at the aclImdb tar")
        self.mode = mode
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        train_pat = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
        tok = re.compile(r"[a-z]+")
        freq: dict = {}
        docs_raw = []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                name = member.name
                is_cur = pat.match(name)
                is_train = train_pat.match(name)
                if not (is_cur or is_train):
                    continue
                words = tok.findall(
                    tf.extractfile(member).read().decode(
                        "utf-8", "ignore").lower())
                if is_train:
                    for w in words:
                        freq[w] = freq.get(w, 0) + 1
                if is_cur:
                    docs_raw.append((words, 0 if "/pos/" in name else 1))
        kept = sorted((w for w, c in freq.items() if c >= cutoff),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(w, unk) for w in ws],
                                np.int64) for ws, _ in docs_raw]
        self.labels = [lb for _, lb in docs_raw]

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])


class Imikolov(Dataset):
    """PTB n-gram dataset (reference text/datasets/imikolov.py). Reads the
    simple-examples tar (`./simple-examples/data/ptb.{train,valid}.txt`);
    yields n-grams (data_type="NGRAM") or (src, trg) sequences ("SEQ")."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=False):
        if download or data_file is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_file")
        split = {"train": "train", "valid": "valid", "test": "test"}[mode]
        lines_train, lines_cur = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                if member.name.endswith("data/ptb.train.txt"):
                    lines_train = tf.extractfile(member).read().decode(
                        "utf-8").splitlines()
                if member.name.endswith(f"data/ptb.{split}.txt"):
                    lines_cur = tf.extractfile(member).read().decode(
                        "utf-8").splitlines()
        freq: dict = {}
        for ln in lines_train:
            for w in ln.split():
                freq[w] = freq.get(w, 0) + 1
        kept = sorted((w for w, c in freq.items()
                       if c >= min_word_freq and w != "<unk>"),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.data = []
        for ln in lines_cur:
            ids = [self.word_idx.get(w, unk)
                   for w in ["<s>"] + ln.split() + ["<e>"]
                   if w in self.word_idx or True]
            ids = [self.word_idx.get(w, unk)
                   for w in ["<s>"] + ln.split() + ["<e>"]]
            if data_type.upper() == "NGRAM":
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        self.data.append(
                            np.asarray(ids[i - window_size:i], np.int64))
            elif data_type.upper() == "SEQ":
                self.data.append((np.asarray(ids[:-1], np.int64),
                                  np.asarray(ids[1:], np.int64)))
            else:
                raise ValueError("data_type must be NGRAM or SEQ")

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx]


class UCIHousing(Dataset):
    """Boston housing regression (reference text/datasets/uci_housing.py).
    Reads the whitespace `housing.data` file; features normalized with the
    reference's train-split statistics convention."""

    N_TRAIN = 406

    def __init__(self, data_file=None, mode="train", download=False):
        if download or data_file is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_file")
        raw = np.loadtxt(data_file).astype(np.float32)
        feats = raw[:, :-1]
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / (mx - mn + 1e-8)
        data = np.concatenate([feats, raw[:, -1:]], axis=1)
        self.data = (data[: self.N_TRAIN] if mode == "train"
                     else data[self.N_TRAIN:])

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1].astype(np.float32), row[-1:].astype(np.float32)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (reference text/datasets/conll05.py). Reads local
    `wordDict/verbDict/targetDict` text files + the prop file (word \t
    predicate \t ... label columns); emits index sequences."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 download=False):
        if download or None in (data_file, word_dict_file, verb_dict_file,
                                target_dict_file):
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass data_file and "
                "the three dict files")

        def load_dict(p):
            with _open_maybe_gz(p, "rt") as f:
                return {ln.strip(): i for i, ln in enumerate(f)
                        if ln.strip()}

        self.word_dict = load_dict(word_dict_file)
        self.verb_dict = load_dict(verb_dict_file)
        self.label_dict = load_dict(target_dict_file)
        unk = self.word_dict.get("<unk>", 0)
        self.samples = []
        with _open_maybe_gz(data_file, "rt") as f:
            words, verbs, labels = [], [], []
            for ln in f:
                ln = ln.strip()
                if not ln:
                    if words and verbs:
                        w_ids = np.asarray(
                            [self.word_dict.get(w, unk) for w in words],
                            np.int64)
                        v_id = np.int64(self.verb_dict.get(verbs[0], 0))
                        l_ids = np.asarray(
                            [self.label_dict.get(l, 0) for l in labels],
                            np.int64)
                        self.samples.append((w_ids, v_id, l_ids))
                    words, verbs, labels = [], [], []
                    continue
                cols = ln.split()
                words.append(cols[0])
                if len(cols) > 1 and cols[1] != "-":
                    verbs.append(cols[1])
                labels.append(cols[-1])
            if words and verbs:
                w_ids = np.asarray(
                    [self.word_dict.get(w, unk) for w in words], np.int64)
                self.samples.append(
                    (w_ids, np.int64(self.verb_dict.get(verbs[0], 0)),
                     np.asarray([self.label_dict.get(l, 0)
                                 for l in labels], np.int64)))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        return self.samples[idx]


class WMT14(Dataset):
    """WMT14 en-fr pairs (reference text/datasets/wmt14.py). Reads parallel
    `<name>.en` / `<name>.fr` local files + optional vocab files; yields
    (src_ids, trg_ids, trg_next_ids) like the reference."""

    def __init__(self, src_file=None, trg_file=None, src_dict_file=None,
                 trg_dict_file=None, dict_size=30000, mode="train",
                 download=False):
        if download or src_file is None or trg_file is None:
            raise RuntimeError(
                "downloads unavailable (zero-egress); pass src/trg files")

        def build_dict(path, dict_file):
            if dict_file and os.path.exists(dict_file):
                with _open_maybe_gz(dict_file, "rt") as f:
                    return {ln.strip(): i for i, ln in enumerate(f)
                            if ln.strip()}
            freq: dict = {}
            with _open_maybe_gz(path, "rt") as f:
                for ln in f:
                    for w in ln.split():
                        freq[w] = freq.get(w, 0) + 1
            kept = sorted(freq, key=lambda w: (-freq[w], w))
            vocab = ["<s>", "<e>", "<unk>"] + kept[: dict_size - 3]
            return {w: i for i, w in enumerate(vocab)}

        self.src_dict = build_dict(src_file, src_dict_file)
        self.trg_dict = build_dict(trg_file, trg_dict_file)
        s_unk = self.src_dict.get("<unk>", 2)
        t_unk = self.trg_dict.get("<unk>", 2)
        bos = self.trg_dict.get("<s>", 0)
        eos = self.trg_dict.get("<e>", 1)
        self.pairs = []
        with _open_maybe_gz(src_file, "rt") as fs, \
                _open_maybe_gz(trg_file, "rt") as ft:
            for s_ln, t_ln in zip(fs, ft):
                s = [self.src_dict.get(w, s_unk) for w in s_ln.split()]
                t = [self.trg_dict.get(w, t_unk) for w in t_ln.split()]
                if not s or not t:
                    continue
                self.pairs.append((
                    np.asarray(s, np.int64),
                    np.asarray([bos] + t, np.int64),
                    np.asarray(t + [eos], np.int64)))

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, idx):
        return self.pairs[idx]
