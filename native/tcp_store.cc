// TCPStore: TCP key-value rendezvous store.
//
// Reference: paddle/phi/core/distributed/store/tcp_store.h:121 +
// tcp_utils.cc — rank 0 hosts a socket server with a string->bytes map;
// clients SET/GET/ADD/WAIT keys to bootstrap process groups.
//
// TPU-native runtime keeps the same role (multi-host bootstrap before
// jax.distributed is up, barrier/elastic bookkeeping). Thread-per-connection
// server, blocking WAIT via condition variable, length-prefixed frames:
//   request:  [u8 op][u32 klen][key][u64 vlen][val]
//   response: [u64 vlen][val]   (ADD returns 8-byte little-endian i64)
// ops: 1=SET 2=GET(blocking until key exists, bounded by client timeout)
//      3=ADD 4=WAIT 5=DELETE

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Server {
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;  // guarded by mu; for shutdown-on-stop
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  bool stopping = false;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_blob(int fd, const std::string& v) {
  uint64_t n = v.size();
  if (!write_full(fd, &n, 8)) return false;
  return v.empty() ? true : write_full(fd, v.data(), v.size());
}

void handle_conn(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t op;
    uint32_t klen;
    uint64_t vlen;
    if (!read_full(fd, &op, 1) || !read_full(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_full(fd, &key[0], klen)) break;
    if (!read_full(fd, &vlen, 8)) break;
    std::string val(vlen, '\0');
    if (vlen && !read_full(fd, &val[0], vlen)) break;

    if (op == 1) {  // SET
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv[key] = val;
      }
      s->cv.notify_all();
      if (!send_blob(fd, "")) break;
    } else if (op == 2 || op == 4) {  // GET (blocking) / WAIT
      std::unique_lock<std::mutex> g(s->mu);
      s->cv.wait(g, [&] { return s->stopping || s->kv.count(key); });
      if (s->stopping) break;
      std::string out = (op == 2) ? s->kv[key] : "";
      g.unlock();
      if (!send_blob(fd, op == 2 ? out : std::string("\x01", 1))) break;
    } else if (op == 3) {  // ADD
      int64_t delta = 0;
      memcpy(&delta, val.data(), std::min<size_t>(8, val.size()));
      int64_t now;
      {
        std::lock_guard<std::mutex> g(s->mu);
        int64_t cur = 0;
        auto it = s->kv.find(key);
        if (it != s->kv.end())
          memcpy(&cur, it->second.data(), std::min<size_t>(8, it->second.size()));
        now = cur + delta;
        s->kv[key] = std::string(reinterpret_cast<char*>(&now), 8);
      }
      s->cv.notify_all();
      if (!send_blob(fd, std::string(reinterpret_cast<char*>(&now), 8))) break;
    } else if (op == 5) {  // DELETE
      {
        std::lock_guard<std::mutex> g(s->mu);
        s->kv.erase(key);
      }
      if (!send_blob(fd, "")) break;
    } else if (op == 6) {  // TRYGET (non-blocking; missing -> 0x00 marker)
      std::string out;
      bool found;
      {
        std::lock_guard<std::mutex> g(s->mu);
        auto it = s->kv.find(key);
        found = it != s->kv.end();
        if (found) out = it->second;
      }
      // prefix byte distinguishes "missing" from "present but empty"
      if (!send_blob(fd, (found ? std::string("\x01", 1) : std::string("\x00", 1)) + out)) break;
    } else {
      // Unknown op (newer client against this server): reply with an error
      // marker instead of dropping the connection, so one unsupported call
      // does not poison the client's cached fd for every later op. The
      // reverse skew (new client, OLD server binary) still drops — rebuild
      // all hosts from the same tree.
      if (!send_blob(fd, std::string("\xff", 1) + "ERR:unknown-op")) break;
    }
  }
  {
    // Remove our fd from conn_fds before closing: stop() shutdowns every fd
    // still listed, and a closed-and-recycled fd number must not be there.
    std::lock_guard<std::mutex> g(s->mu);
    auto it = std::find(s->conn_fds.begin(), s->conn_fds.end(), fd);
    if (it != s->conn_fds.end()) s->conn_fds.erase(it);
  }
  ::close(fd);
}

}  // namespace

extern "C" {

void* tcp_store_server_start(int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* s = new Server();
  s->listen_fd = fd;
  s->accept_thread = std::thread([s] {
    for (;;) {
      int cfd = ::accept(s->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen fd closed -> shutdown
      std::lock_guard<std::mutex> g(s->mu);
      if (s->stopping) {
        ::close(cfd);
        break;
      }
      s->conn_fds.push_back(cfd);
      s->conns.emplace_back(handle_conn, s, cfd);
    }
  });
  return s;
}

int tcp_store_server_port(void* sp) {
  auto* s = static_cast<Server*>(sp);
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len) != 0)
    return -1;
  return ntohs(addr.sin_port);
}

void tcp_store_server_stop(void* sp) {
  // Handler threads may be blocked in cv.wait (stopping flag + notify wakes
  // them) or in read() (shutdown on their fd wakes them with EOF). Join —
  // never detach — every thread before freeing the Server, otherwise a
  // mid-process stop races threads still touching s->mu/s->kv.
  auto* s = static_cast<Server*>(sp);
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->stopping = true;
    for (int cfd : s->conn_fds) ::shutdown(cfd, SHUT_RDWR);
  }
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // accept_thread has exited, so no more threads are appended to conns.
  for (auto& t : s->conns)
    if (t.joinable()) t.join();
  delete s;
}

// timeout_ms bounds connect(); io_timeout_ms bounds each blocking
// GET/WAIT/response read (rendezvous waits legitimately run minutes, so this
// is a separate, much longer bound). A timed-out request leaves the
// length-prefixed stream desynchronized — callers must treat failure as
// fatal for the connection, not retry on the same fd.
intptr_t tcp_store_connect(const char* host, int port, int timeout_ms,
                           int io_timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 3000);
  while (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    if (std::chrono::steady_clock::now() > deadline) {
      ::close(fd);
      return -1;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  // Bound GET/WAIT on the connected socket too (the protocol contract
  // above): a key that is never set must raise on the client instead of
  // hanging the rank forever.
  if (io_timeout_ms > 0) {
    timeval tv{};
    tv.tv_sec = io_timeout_ms / 1000;
    tv.tv_usec = (io_timeout_ms % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
  return fd;
}

static int request(int fd, uint8_t op, const char* key, const void* val,
                   uint64_t vlen, std::string* out) {
  uint32_t klen = static_cast<uint32_t>(strlen(key));
  if (!write_full(fd, &op, 1) || !write_full(fd, &klen, 4) ||
      !write_full(fd, key, klen) || !write_full(fd, &vlen, 8))
    return -1;
  if (vlen && !write_full(fd, val, vlen)) return -1;
  uint64_t rlen;
  if (!read_full(fd, &rlen, 8)) return -1;
  out->resize(rlen);
  if (rlen && !read_full(fd, &(*out)[0], rlen)) return -1;
  return 0;
}

int tcp_store_set(intptr_t fd, const char* key, const void* val, long vlen) {
  std::string out;
  return request(static_cast<int>(fd), 1, key, val,
                 static_cast<uint64_t>(vlen), &out);
}

long tcp_store_get(intptr_t fd, const char* key, void* buf, long cap) {
  std::string out;
  if (request(static_cast<int>(fd), 2, key, nullptr, 0, &out) != 0) return -1;
  long n = static_cast<long>(out.size());
  memcpy(buf, out.data(), std::min<long>(n, cap));
  return n;
}

// errno-style: returns 0 and writes the new counter into *out, or -1 on
// failure (a plain long long return could not distinguish a legitimate
// counter value of -1 from an error).
int tcp_store_add(intptr_t fd, const char* key, long long delta,
                  long long* out) {
  std::string resp;
  if (request(static_cast<int>(fd), 3, key, &delta, 8, &resp) != 0 ||
      resp.size() < 8)
    return -1;
  memcpy(out, resp.data(), 8);
  return 0;
}

int tcp_store_wait(intptr_t fd, const char* key) {
  std::string out;
  return request(static_cast<int>(fd), 4, key, nullptr, 0, &out);
}

// Non-blocking probe: returns the value length (copied into buf up to cap)
// when present, -2 when the key is missing, -1 on transport failure.
long tcp_store_tryget(intptr_t fd, const char* key, void* buf, long cap) {
  std::string out;
  if (request(static_cast<int>(fd), 6, key, nullptr, 0, &out) != 0) return -1;
  if (out.empty() || out[0] == '\0') return -2;
  // '\xff' is the server's unknown-op error reply (version skew) — a
  // protocol error, not a stored value.
  if (out[0] == '\xff') return -1;
  long n = static_cast<long>(out.size()) - 1;
  memcpy(buf, out.data() + 1, std::min<long>(n, cap));
  return n;
}

int tcp_store_delete(intptr_t fd, const char* key) {
  std::string out;
  return request(static_cast<int>(fd), 5, key, nullptr, 0, &out);
}

void tcp_store_close(intptr_t fd) { ::close(static_cast<int>(fd)); }

}  // extern "C"
