// C inference API over the paddle_tpu Predictor (reference analog:
// paddle/fluid/inference/capi_exp/pd_inference_api.h, the
// paddle_inference_c library that C/Go deployments link against).
//
// TPU-native design: the inference runtime IS the Python-side
// TranslatedLayer playing a compiled XLA executable; this shim embeds (or
// attaches to) CPython and drives paddle_tpu.inference through the C ABI.
// - Standalone C/Go program: the first call initializes an interpreter.
// - Inside an existing Python process (ctypes tests, plugins): attaches to
//   the running interpreter via PyGILState.
// Data moves through the buffer protocol (no numpy C headers needed).
//
// Build: make -C native libpaddle_tpu_c.so

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {

typedef int32_t PD_Bool;

struct PD_Config {
  std::string prog_file;
};

struct PD_Predictor {
  PyObject* pred;  // paddle_tpu.inference.Predictor
};

struct PD_Tensor {
  PyObject* handle;  // paddle_tpu.inference._Handle
  std::vector<int32_t> shape;
  // output handles refresh their cached shape from the live array on every
  // shape query: a handle fetched BEFORE PD_PredictorRun would otherwise
  // keep a stale/empty shape, and a caller sizing its buffer from it
  // overflows when the post-run copy delivers more bytes
  bool from_output = false;
};

namespace {

// ensure an interpreter exists and PYTHONPATH covers the repo; returns a
// held GIL state. Every exported function brackets with Gil g;
struct Gil {
  PyGILState_STATE st;
  Gil() {
    // first-use interpreter init must be raced-safely: two threads of a
    // C/Go host can hit the API concurrently at startup
    static std::once_flag init_once;
    std::call_once(init_once, [] {
      if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        // embedding case: release the main thread's GIL so PyGILState works
        (void)PyEval_SaveThread();
      }
    });
    st = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st); }
};

PyObject* inference_module() {
  PyObject* m = PyImport_ImportModule("paddle_tpu.inference");
  if (!m) PyErr_Print();
  return m;
}

// contiguous numpy array of `dtype` with `shape`; borrowed refs managed by
// caller
PyObject* np_empty(const std::vector<int32_t>& shape, const char* dtype) {
  PyObject* np = PyImport_ImportModule("numpy");
  if (!np) return nullptr;
  PyObject* dims = PyTuple_New((Py_ssize_t)shape.size());
  for (size_t i = 0; i < shape.size(); ++i)
    PyTuple_SET_ITEM(dims, (Py_ssize_t)i, PyLong_FromLong(shape[i]));
  PyObject* arr = PyObject_CallMethod(np, "empty", "Os", dims, dtype);
  Py_DECREF(dims);
  Py_DECREF(np);
  return arr;
}

size_t numel(const std::vector<int32_t>& shape) {
  size_t n = 1;
  for (int32_t d : shape) n *= (size_t)d;
  return n;
}

void copy_from_cpu(PD_Tensor* t, const void* data, const char* dtype,
                   size_t elem) {
  Gil g;
  PyObject* arr = np_empty(t->shape, dtype);
  if (!arr) { PyErr_Print(); return; }
  Py_buffer view;
  if (PyObject_GetBuffer(arr, &view, PyBUF_CONTIG) == 0) {
    std::memcpy(view.buf, data, numel(t->shape) * elem);
    PyBuffer_Release(&view);
    PyObject* r = PyObject_CallMethod(t->handle, "copy_from_cpu", "O", arr);
    if (!r) PyErr_Print();
    Py_XDECREF(r);
  }
  Py_DECREF(arr);
}

void copy_to_cpu(PD_Tensor* t, void* data, size_t elem) {
  Gil g;
  PyObject* arr = PyObject_CallMethod(t->handle, "copy_to_cpu", nullptr);
  if (!arr) { PyErr_Print(); return; }
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* c = PyObject_CallMethod(np, "ascontiguousarray", "O", arr);
  Py_DECREF(np);
  Py_DECREF(arr);
  if (!c) { PyErr_Print(); return; }
  Py_buffer view;
  if (PyObject_GetBuffer(c, &view, PyBUF_CONTIG_RO) == 0) {
    // clamp to the CALLER-VISIBLE size: the caller sized `data` from the
    // cached shape (PD_TensorGetShape), so if the live array grew since —
    // output handle fetched before PD_PredictorRun, shapes refreshed by a
    // later run — copying view.len would overflow the caller's buffer
    size_t cap = numel(t->shape) * elem;
    std::memcpy(data, view.buf,
                (size_t)view.len < cap ? (size_t)view.len : cap);
    PyBuffer_Release(&view);
  }
  Py_DECREF(c);
}

void refresh_shape(PD_Tensor* t) {
  // shape of the handle's current array (valid after a run)
  PyObject* arr = PyObject_CallMethod(t->handle, "copy_to_cpu", nullptr);
  if (!arr) { PyErr_Clear(); return; }
  PyObject* shp = PyObject_GetAttrString(arr, "shape");
  if (shp) {
    t->shape.clear();
    Py_ssize_t n = PyTuple_Size(shp);
    for (Py_ssize_t i = 0; i < n; ++i)
      t->shape.push_back(
          (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(shp, i)));
    Py_DECREF(shp);
  }
  Py_DECREF(arr);
}

std::string nth_name(PD_Predictor* p, const char* method, int i) {
  PyObject* names = PyObject_CallMethod(p->pred, method, nullptr);
  if (!names) { PyErr_Print(); return ""; }
  std::string out;
  PyObject* item = PySequence_GetItem(names, i);
  if (item) {
    const char* u = PyUnicode_AsUTF8(item);
    if (u) out = u; else PyErr_Clear();
    Py_DECREF(item);
  } else {
    // out-of-range index: a pending IndexError must not leak into the
    // host interpreter (attach path) or later C API calls
    PyErr_Clear();
  }
  Py_DECREF(names);
  return out;
}

int name_count(PD_Predictor* p, const char* method) {
  Gil g;
  PyObject* names = PyObject_CallMethod(p->pred, method, nullptr);
  if (!names) { PyErr_Print(); return 0; }
  int n = (int)PySequence_Size(names);
  Py_DECREF(names);
  return n;
}

PD_Tensor* get_handle(PD_Predictor* p, const char* method, const char* name) {
  Gil g;
  PyObject* h = PyObject_CallMethod(p->pred, method, "s", name);
  if (!h) { PyErr_Print(); return nullptr; }
  PD_Tensor* t = new PD_Tensor();
  t->handle = h;
  return t;
}

thread_local std::string g_name_buf;

}  // namespace

// ---------------------------------------------------------------------- //
// config
// ---------------------------------------------------------------------- //

PD_Config* PD_ConfigCreate() { return new PD_Config(); }

void PD_ConfigDestroy(PD_Config* c) { delete c; }

void PD_ConfigSetModel(PD_Config* c, const char* prog_file,
                       const char* params_file) {
  (void)params_file;  // single-artifact format: weights ride the program
  c->prog_file = prog_file ? prog_file : "";
}

void PD_ConfigSetProgFile(PD_Config* c, const char* prog_file) {
  c->prog_file = prog_file ? prog_file : "";
}

// ---------------------------------------------------------------------- //
// predictor
// ---------------------------------------------------------------------- //

PD_Predictor* PD_PredictorCreate(PD_Config* c) {
  Gil g;
  PyObject* m = inference_module();
  if (!m) return nullptr;
  PyObject* cfg =
      PyObject_CallMethod(m, "Config", "s", c->prog_file.c_str());
  PyObject* pred =
      cfg ? PyObject_CallMethod(m, "create_predictor", "O", cfg) : nullptr;
  Py_XDECREF(cfg);
  Py_DECREF(m);
  if (!pred) { PyErr_Print(); return nullptr; }
  PD_Predictor* p = new PD_Predictor();
  p->pred = pred;
  return p;
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (!p) return;
  Gil g;
  Py_XDECREF(p->pred);
  delete p;
}

size_t PD_PredictorGetInputNum(PD_Predictor* p) {
  return (size_t)name_count(p, "get_input_names");
}

size_t PD_PredictorGetOutputNum(PD_Predictor* p) {
  return (size_t)name_count(p, "get_output_names");
}

const char* PD_PredictorGetInputNameByIndex(PD_Predictor* p, int i) {
  Gil g;
  g_name_buf = nth_name(p, "get_input_names", i);
  return g_name_buf.c_str();
}

const char* PD_PredictorGetOutputNameByIndex(PD_Predictor* p, int i) {
  Gil g;
  g_name_buf = nth_name(p, "get_output_names", i);
  return g_name_buf.c_str();
}

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name) {
  return get_handle(p, "get_input_handle", name);
}

PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name) {
  PD_Tensor* t = get_handle(p, "get_output_handle", name);
  if (t) {
    Gil g;
    t->from_output = true;
    refresh_shape(t);
  }
  return t;
}

PD_Bool PD_PredictorRun(PD_Predictor* p) {
  Gil g;
  PyObject* r = PyObject_CallMethod(p->pred, "run", nullptr);
  if (!r) { PyErr_Print(); return 0; }
  Py_DECREF(r);
  return 1;
}

// ---------------------------------------------------------------------- //
// tensors
// ---------------------------------------------------------------------- //

void PD_TensorDestroy(PD_Tensor* t) {
  if (!t) return;
  Gil g;
  Py_XDECREF(t->handle);
  delete t;
}

void PD_TensorReshape(PD_Tensor* t, size_t ndim, const int32_t* shape) {
  t->shape.assign(shape, shape + ndim);
}

// Output handles created before the predictor ran have no shape yet; the
// lazy refresh below fills it on the first query after a run instead of
// leaving the caller to size its buffer from an empty shape. Handles with
// a known shape are immutable in this runtime (Predictor.run builds fresh
// handles), so non-empty shapes are never re-queried — and the memcpy
// clamp in copy_to_cpu stays the hard overflow guarantee either way.
size_t PD_TensorGetNumDims(PD_Tensor* t) {
  if (t->from_output && t->shape.empty()) {
    Gil g;
    refresh_shape(t);
  }
  return t->shape.size();
}

void PD_TensorGetShape(PD_Tensor* t, int32_t* out) {
  if (t->from_output && t->shape.empty()) {
    Gil g;
    refresh_shape(t);
  }
  std::memcpy(out, t->shape.data(), t->shape.size() * sizeof(int32_t));
}

void PD_TensorCopyFromCpuFloat(PD_Tensor* t, const float* data) {
  copy_from_cpu(t, data, "float32", sizeof(float));
}

void PD_TensorCopyFromCpuInt32(PD_Tensor* t, const int32_t* data) {
  copy_from_cpu(t, data, "int32", sizeof(int32_t));
}

void PD_TensorCopyFromCpuInt64(PD_Tensor* t, const int64_t* data) {
  copy_from_cpu(t, data, "int64", sizeof(int64_t));
}

void PD_TensorCopyToCpuFloat(PD_Tensor* t, float* data) {
  copy_to_cpu(t, data, sizeof(float));
}

void PD_TensorCopyToCpuInt32(PD_Tensor* t, int32_t* data) {
  copy_to_cpu(t, data, sizeof(int32_t));
}

void PD_TensorCopyToCpuInt64(PD_Tensor* t, int64_t* data) {
  copy_to_cpu(t, data, sizeof(int64_t));
}

}  // extern "C"
