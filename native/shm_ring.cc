// Shared-memory ring buffer for DataLoader worker -> trainer batch transfer.
//
// Reference: the reference moves batches from multiprocess workers through
// shared memory (python/paddle/io/dataloader/worker.py + its C++ data_feed,
// paddle/fluid/framework/data_feed.cc) to avoid pickling tensors through
// pipes.
//
// Design: one POSIX shm segment = [Header | data]; variable-size records
// ([u64 len][payload]) in a circular byte buffer; process-shared mutex +
// condvars for blocking push/pop. Single producer / single consumer per ring
// (DataLoader uses one ring per worker, reading round-robin).

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t capacity;  // data bytes
  uint64_t head;      // read offset
  uint64_t tail;      // write offset
  uint64_t used;      // bytes in buffer
  uint32_t closed;
};

struct Ring {
  Header* h;
  char* data;
  uint64_t capacity;
  int fd;
  bool owner;
  char name[256];
};

void write_bytes(Ring* r, const char* src, uint64_t n) {
  uint64_t tail = r->h->tail;
  uint64_t first = std::min(n, r->capacity - tail);
  memcpy(r->data + tail, src, first);
  if (n > first) memcpy(r->data, src + first, n - first);
  r->h->tail = (tail + n) % r->capacity;
  r->h->used += n;
}

void read_bytes(Ring* r, char* dst, uint64_t n) {
  uint64_t head = r->h->head;
  uint64_t first = std::min(n, r->capacity - head);
  memcpy(dst, r->data + head, first);
  if (n > first) memcpy(dst + first, r->data, n - first);
  r->h->head = (head + n) % r->capacity;
  r->h->used -= n;
}

}  // namespace

extern "C" {

void* shm_ring_create(const char* name, long capacity) {
  // O_EXCL without a pre-unlink: a name collision (two rings generating the
  // same name) must fail loudly rather than silently unlinking the segment
  // another worker is attached to.
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + static_cast<uint64_t>(capacity);
  if (ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    ::close(fd);
    shm_unlink(name);
    return nullptr;
  }
  auto* h = static_cast<Header*>(mem);
  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_empty, &ca);
  pthread_cond_init(&h->not_full, &ca);
  h->capacity = static_cast<uint64_t>(capacity);
  h->head = h->tail = h->used = 0;
  h->closed = 0;
  auto* r = new Ring();
  r->h = h;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->capacity = h->capacity;
  r->fd = fd;
  r->owner = true;
  snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

void* shm_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size),
                   PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* h = static_cast<Header*>(mem);
  auto* r = new Ring();
  r->h = h;
  r->data = static_cast<char*>(mem) + sizeof(Header);
  r->capacity = h->capacity;
  r->fd = fd;
  r->owner = false;
  snprintf(r->name, sizeof(r->name), "%s", name);
  return r;
}

// push one record; blocks while full. returns 0 ok, -1 closed, -2 too large
int shm_ring_push(void* rp, const void* buf, long n) {
  auto* r = static_cast<Ring*>(rp);
  uint64_t need = 8 + static_cast<uint64_t>(n);
  if (need > r->capacity) return -2;
  pthread_mutex_lock(&r->h->mu);
  while (r->capacity - r->h->used < need && !r->h->closed)
    pthread_cond_wait(&r->h->not_full, &r->h->mu);
  if (r->h->closed) {
    pthread_mutex_unlock(&r->h->mu);
    return -1;
  }
  uint64_t len = static_cast<uint64_t>(n);
  write_bytes(r, reinterpret_cast<const char*>(&len), 8);
  write_bytes(r, static_cast<const char*>(buf), len);
  pthread_cond_signal(&r->h->not_empty);
  pthread_mutex_unlock(&r->h->mu);
  return 0;
}

// pop one record into buf (cap bytes); blocks while empty.
// returns record length, -1 closed+drained, -2 buffer too small (record kept)
long shm_ring_pop(void* rp, void* buf, long cap) {
  auto* r = static_cast<Ring*>(rp);
  pthread_mutex_lock(&r->h->mu);
  while (r->h->used == 0 && !r->h->closed)
    pthread_cond_wait(&r->h->not_empty, &r->h->mu);
  if (r->h->used == 0 && r->h->closed) {
    pthread_mutex_unlock(&r->h->mu);
    return -1;
  }
  uint64_t len;
  uint64_t head = r->h->head;  // peek
  uint64_t first = std::min<uint64_t>(8, r->capacity - head);
  memcpy(&len, r->data + head, first);
  if (first < 8)
    memcpy(reinterpret_cast<char*>(&len) + first, r->data, 8 - first);
  if (static_cast<long>(len) > cap) {
    pthread_mutex_unlock(&r->h->mu);
    return -2;
  }
  read_bytes(r, reinterpret_cast<char*>(&len), 8);  // consume header
  read_bytes(r, static_cast<char*>(buf), len);
  pthread_cond_signal(&r->h->not_full);
  pthread_mutex_unlock(&r->h->mu);
  return static_cast<long>(len);
}

// non-blocking size probe of next record (-1 if empty)
long shm_ring_peek(void* rp) {
  auto* r = static_cast<Ring*>(rp);
  pthread_mutex_lock(&r->h->mu);
  long out = -1;
  if (r->h->used >= 8) {
    uint64_t len;
    uint64_t head = r->h->head;
    uint64_t first = std::min<uint64_t>(8, r->capacity - head);
    memcpy(&len, r->data + head, first);
    if (first < 8)
      memcpy(reinterpret_cast<char*>(&len) + first, r->data, 8 - first);
    out = static_cast<long>(len);
  }
  pthread_mutex_unlock(&r->h->mu);
  return out;
}

void shm_ring_close(void* rp) {
  auto* r = static_cast<Ring*>(rp);
  pthread_mutex_lock(&r->h->mu);
  r->h->closed = 1;
  pthread_cond_broadcast(&r->h->not_empty);
  pthread_cond_broadcast(&r->h->not_full);
  pthread_mutex_unlock(&r->h->mu);
}

void shm_ring_destroy(void* rp) {
  auto* r = static_cast<Ring*>(rp);
  bool owner = r->owner;
  uint64_t total = sizeof(Header) + r->capacity;
  munmap(r->h, total);
  ::close(r->fd);
  if (owner) shm_unlink(r->name);
  delete r;
}

}  // extern "C"
