// Example custom operator against the XLA FFI — the TPU-native analog of the
// reference's custom C++ op extension (paddle/fluid/framework/
// custom_operator.cc + PD_BUILD_OP macros in paddle/extension.h): a host
// kernel registered as an XLA custom call, loadable at runtime via
// paddle_tpu.utils.cpp_extension.
//
// axpby: out = a * x + b * y  (elementwise, f32), plus its backward kernels
// (dx = a * g, dy = b * g) so the python wrapper can wire a custom_vjp.
//
// Built separately from libpaddle_tpu_native.so because it needs the XLA FFI
// headers shipped with jaxlib (jax.ffi.include_dir()).

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error AxpbyImpl(float a, float b, ffi::Buffer<ffi::F32> x,
                            ffi::Buffer<ffi::F32> y,
                            ffi::ResultBuffer<ffi::F32> out) {
  size_t n = x.element_count();
  const float* xp = x.typed_data();
  const float* yp = y.typed_data();
  float* op = out->typed_data();
  for (size_t i = 0; i < n; ++i) op[i] = a * xp[i] + b * yp[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(Axpby, AxpbyImpl,
                              ffi::Ffi::Bind()
                                  .Attr<float>("a")
                                  .Attr<float>("b")
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());

static ffi::Error ScaleImpl(float c, ffi::Buffer<ffi::F32> g,
                            ffi::ResultBuffer<ffi::F32> out) {
  size_t n = g.element_count();
  const float* gp = g.typed_data();
  float* op = out->typed_data();
  for (size_t i = 0; i < n; ++i) op[i] = c * gp[i];
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(Scale, ScaleImpl,
                              ffi::Ffi::Bind()
                                  .Attr<float>("c")
                                  .Arg<ffi::Buffer<ffi::F32>>()
                                  .Ret<ffi::Buffer<ffi::F32>>());
