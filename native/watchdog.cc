// Collective hang watchdog.
//
// Reference: CommTaskManager (paddle/phi/core/distributed/comm_task_manager.h:37)
// + CommTask::IsTimeout (comm_task.h:127) — a background thread that tracks
// every in-flight collective and logs rings stuck past the timeout (the
// practical distributed deadlock detector).
//
// TPU-native runtime: collectives are compiled into XLA programs, so the unit
// tracked is a dispatched step/collective *region* (registered around
// blocking device syncs). The monitor thread marks tasks that exceed their
// deadline; python polls reports and raises/logs.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>

namespace {

using Clock = std::chrono::steady_clock;

struct Task {
  std::string desc;
  Clock::time_point start;
  long timeout_ms;
  bool reported = false;
};

struct Watchdog {
  std::mutex mu;
  std::condition_variable cv;
  std::map<long long, Task> tasks;
  std::string report;  // accumulated timeout lines
  long long next_id = 1;
  long default_timeout_ms;
  long long n_timeouts = 0;
  bool stopping = false;
  std::thread monitor;
};

void monitor_loop(Watchdog* w) {
  std::unique_lock<std::mutex> g(w->mu);
  while (!w->stopping) {
    w->cv.wait_for(g, std::chrono::milliseconds(50));
    auto now = Clock::now();
    for (auto& [id, t] : w->tasks) {
      if (t.reported) continue;
      auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                    now - t.start)
                    .count();
      if (ms > t.timeout_ms) {
        t.reported = true;
        w->n_timeouts++;
        w->report += "[watchdog] task " + std::to_string(id) + " '" + t.desc +
                     "' exceeded " + std::to_string(t.timeout_ms) + "ms (" +
                     std::to_string(ms) + "ms elapsed)\n";
      }
    }
  }
}

}  // namespace

extern "C" {

void* watchdog_create(long default_timeout_ms) {
  auto* w = new Watchdog();
  w->default_timeout_ms = default_timeout_ms;
  w->monitor = std::thread(monitor_loop, w);
  return w;
}

void watchdog_destroy(void* wp) {
  auto* w = static_cast<Watchdog*>(wp);
  {
    std::lock_guard<std::mutex> g(w->mu);
    w->stopping = true;
  }
  w->cv.notify_all();
  if (w->monitor.joinable()) w->monitor.join();
  delete w;
}

long long watchdog_register(void* wp, const char* desc, long timeout_ms) {
  auto* w = static_cast<Watchdog*>(wp);
  std::lock_guard<std::mutex> g(w->mu);
  long long id = w->next_id++;
  w->tasks[id] = Task{desc ? desc : "", Clock::now(),
                      timeout_ms > 0 ? timeout_ms : w->default_timeout_ms};
  return id;
}

void watchdog_complete(void* wp, long long id) {
  auto* w = static_cast<Watchdog*>(wp);
  std::lock_guard<std::mutex> g(w->mu);
  w->tasks.erase(id);
}

long long watchdog_timeout_count(void* wp) {
  auto* w = static_cast<Watchdog*>(wp);
  std::lock_guard<std::mutex> g(w->mu);
  return w->n_timeouts;
}

// drain accumulated report text; returns bytes written (report cleared)
long watchdog_drain_report(void* wp, char* buf, long cap) {
  auto* w = static_cast<Watchdog*>(wp);
  std::lock_guard<std::mutex> g(w->mu);
  long n = static_cast<long>(w->report.size());
  if (n > cap) n = cap;
  memcpy(buf, w->report.data(), n);
  w->report.erase(0, n);
  return n;
}

long long watchdog_inflight(void* wp) {
  auto* w = static_cast<Watchdog*>(wp);
  std::lock_guard<std::mutex> g(w->mu);
  return static_cast<long long>(w->tasks.size());
}

}  // extern "C"
