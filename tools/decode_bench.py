#!/usr/bin/env python
"""Decode/serving throughput bench: tokens/s for the continuous-batching
engine (inference/serving.py) on gpt3-125M-shaped decode.

Prints one JSON line per configuration: prefill + steady-state decode
tokens/s at several batch sizes, with and without weight-only int8.
Run on the real chip via tools/hw_session.sh step 7; CPU runs are smoke
only."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ContinuousBatchingEngine
    from paddle_tpu.models import GPTForCausalLM, gpt3_125m, gpt3_tiny

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg_fn = gpt3_125m if on_tpu else gpt3_tiny
    seq_len = 1024 if on_tpu else 64
    new_tokens = 128 if on_tpu else 8

    for quantized in (False, True):
        paddle.seed(0)
        model = GPTForCausalLM(cfg_fn())
        if quantized:
            from paddle_tpu.nn.quant import quantize_for_inference

            quantize_for_inference(model)
        for B in (1, 8) if on_tpu else (2,):
            eng = ContinuousBatchingEngine(model, max_batch_size=B,
                                           max_seq_len=seq_len)
            rng = np.random.default_rng(0)
            for _ in range(B):
                eng.add_request(
                    rng.integers(0, model.config.vocab_size, 32)
                    .astype(np.int32),
                    max_new_tokens=new_tokens, temperature=0.0)
            eng.step()  # admit + compile
            t0 = time.perf_counter()
            n_tokens = 0
            while any(r is not None for r in eng.active):
                n_tokens += len(eng.step())
            dt = time.perf_counter() - t0
            print(json.dumps({
                "metric": "decode_tokens_per_sec",
                "batch": B,
                "quantized": quantized,
                "value": round(n_tokens / max(dt, 1e-9), 1),
                "unit": "tok/s",
                "platform": jax.devices()[0].platform,
            }), flush=True)


if __name__ == "__main__":
    main()
