"""Per-component timing breakdown on the current backend (meant for TPU).

Times each suspect in isolation so the 1/MFU budget can be attributed:
  matmul peak sanity, flash-attention kernel fwd / fwd+bwd (Pallas vs XLA
  composite), lm-head+CE, MLP-shaped matmuls, full fwd, full train step.

Usage:  python tools/perf_breakdown.py [gpt3_125m|gpt3_350m]
Prints one JSON line per probe: {"probe", "ms", "tflops", "eff_vs_peak"}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _host_sync(out):
    """Force a REAL device->host fetch. Round-4 lesson: through the
    experimental axon tunnel jax.block_until_ready returned before device
    execution finished, so probes measured dispatch latency (681% of peak,
    8192^3 matmuls in 0.03ms). Fetching a literal cannot lie: TPU execution
    is in-order per device, so materializing the last output on the host
    proves every prior dispatch completed."""
    leaf = jax.tree.leaves(out)[0]
    # slice on DEVICE first so only one element crosses the bus — fetching
    # the whole array (e.g. a 128MB matmul output) would inflate the timed
    # region with transfer time
    one = leaf.ravel()[0:1] if getattr(leaf, "ndim", 0) else leaf
    return np.asarray(jax.device_get(one))


def timeit(fn, *args, reps=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    _host_sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _host_sync(out)  # in-order device stream => all reps done
    return (time.perf_counter() - t0) / reps


def report(probe, dt, flops, peak):
    tf = flops / dt / 1e12
    eff = flops / dt / peak
    line = {
        "probe": probe,
        "ms": round(dt * 1e3, 3),
        "tflops": round(tf, 1),
        "eff_vs_peak": round(eff, 3),
    }
    if eff > 1.1:
        # physically impossible — the timed loop did not synchronize
        line["invalid"] = "eff>110% of peak: timing not synchronized, discard"
    print(json.dumps(line), flush=True)
    return line


def main():
    cfg_name = sys.argv[1] if len(sys.argv) > 1 else "gpt3_125m"
    backend = jax.default_backend()
    print(json.dumps({"probe": "backend", "name": backend,
                      "device": str(getattr(jax.devices()[0], "device_kind", ""))}),
          flush=True)
    from bench import _peak_flops

    peak, kind = _peak_flops(jax.devices()[0])
    if backend == "cpu":
        peak = 1e12  # nominal, so the script still runs for smoke

    B, S = (8, 2048) if backend != "cpu" else (2, 256)
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt3_125m, gpt3_350m, GPTForCausalLM, GPTPretrainingCriterion

    if backend == "cpu":
        from paddle_tpu.models import gpt3_tiny

        cfg = gpt3_tiny()
        cfg.max_position_embeddings = S
    else:
        cfg = {"gpt3_125m": gpt3_125m, "gpt3_350m": gpt3_350m}[cfg_name](
            max_position_embeddings=S)
    H, L, nh, D = cfg.hidden_size, cfg.num_layers, cfg.num_heads, cfg.head_dim
    V = cfg.vocab_size
    key = jax.random.PRNGKey(0)

    # 1. matmul peak sanity: can this chip/tunnel hit its spec at all?
    for n in ((4096, 8192) if backend != "cpu" else (512,)):
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        f = jax.jit(lambda x, y: x @ y)
        dt = timeit(f, a, a)
        report(f"matmul_bf16_{n}", dt, 2.0 * n ** 3, peak)

    # 2. MLP-shaped matmul chain (the non-attention compute shape)
    x = jax.random.normal(key, (B * S, H), jnp.bfloat16)
    w1 = jax.random.normal(key, (H, 4 * H), jnp.bfloat16)
    w2 = jax.random.normal(key, (4 * H, H), jnp.bfloat16)

    def mlp(x, w1, w2):
        return jax.nn.gelu(x @ w1) @ w2

    dt = timeit(jax.jit(mlp), x, w1, w2)
    report("mlp_fwd", dt, 2 * 2 * B * S * H * 4 * H, peak)

    grad_mlp = jax.jit(jax.grad(lambda x, w1, w2: mlp(x, w1, w2).astype(jnp.float32).sum(),
                                argnums=(1, 2)))
    dt = timeit(grad_mlp, x, w1, w2)
    report("mlp_bwd", dt, 2 * 2 * 2 * B * S * H * 4 * H, peak)

    # 3. attention: Pallas kernel vs XLA composite, fwd and fwd+bwd
    attn_flops_fwd = 2 * 2 * B * nh * S * S * D  # qk + pv (causal halves it)
    q = jax.random.normal(key, (B, S, nh, D), jnp.bfloat16)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd
    from paddle_tpu.nn.functional.flash_attention import _ref_attention

    def pal(q):
        return flash_attention_fwd(q, q, q, causal=True)

    def comp(q):
        return _ref_attention(q, q, q, causal=True)

    for name, fn in (("attn_pallas", pal), ("attn_xla", comp)):
        try:
            dt = timeit(jax.jit(fn), q)
            report(name + "_fwd", dt, attn_flops_fwd / 2, peak)
            g = jax.jit(jax.grad(lambda q: fn(q).astype(jnp.float32).sum()))
            dt = timeit(g, q)
            report(name + "_fwdbwd", dt, attn_flops_fwd / 2 * 3.5, peak)
        except Exception as e:
            print(json.dumps({"probe": name, "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)

    # 4. lm head + cross entropy (tied-embedding shape)
    h = jax.random.normal(key, (B, S, H), jnp.bfloat16)
    w = jax.random.normal(key, (V, H), jnp.bfloat16)
    lab = jax.random.randint(key, (B, S), 0, V)

    def head_ce(h, w, lab):
        logits = h @ w.T
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, lab[..., None], axis=-1).mean()

    dt = timeit(jax.jit(head_ce), h, w, lab)
    report("head_ce_fwd", dt, 2 * B * S * H * V, peak)
    g = jax.jit(jax.grad(head_ce, argnums=(0, 1)))
    dt = timeit(g, h, w, lab)
    report("head_ce_fwdbwd", dt, 3 * 2 * B * S * H * V, peak)

    # 5. full model fwd and full train step
    paddle.seed(0)
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh,
        amp_level="O2", amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, V, (B, S)))
    labels = paddle.to_tensor(rng.integers(0, V, (B, S)))

    n_params = cfg.num_params(include_embeddings=False) + V * H
    tok = B * S
    step_flops = 6.0 * n_params * tok + 12.0 * L * H * S * tok

    def run_step(_i):
        return step(ids, labels)

    dt = timeit(lambda: step(ids, labels)._value, reps=5, warmup=2)
    report("train_step", dt, step_flops, peak)

    # 6. eval (fwd-only) pass through the same machinery
    dt = timeit(lambda: step.evaluate(ids, labels)._value, reps=5, warmup=2)
    report("eval_fwd", dt, step_flops / 3.0, peak)


if __name__ == "__main__":
    main()
