"""Per-component timing breakdown on the current backend (meant for TPU).

Times each suspect in isolation so the 1/MFU budget can be attributed:
  matmul peak sanity, qkvo-projection matmuls, MLP chain, flash-attention
  Pallas vs XLA composite (fwd and fwd+bwd), lm-head+CE, full train step.

Timing method (round-5): every kernel probe runs ITERS copies of the op
inside one jitted lax.scan, so per-dispatch overhead (≈3-4ms through the
axon TPU tunnel — it swamped every sub-5ms probe in round 4) divides out;
the loop carry feeds each iteration so XLA cannot CSE or DCE the work. A
`dispatch_overhead` probe reports the per-call floor separately. Every
timing ends in a REAL device->host fetch: through the tunnel,
block_until_ready alone returned before execution finished (681%-of-peak
"measurements" in round 4's artifact).

Usage:  python tools/perf_breakdown.py [gpt3_125m|gpt3_350m]
Prints one JSON line per probe: {"probe", "ms", "tflops", "eff_vs_peak"}.
"""

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 20


def _host_sync(out):
    """Force a REAL device->host fetch (see module docstring). Slices on
    DEVICE first so only one element crosses the bus."""
    leaf = jax.tree.leaves(out)[0]
    one = leaf.ravel()[0:1] if getattr(leaf, "ndim", 0) else leaf
    return np.asarray(jax.device_get(one))


def timeit_wall(fn, *args, reps=5, warmup=2):
    """Wall-clock per-call timing (includes dispatch overhead) — only for
    big probes (>=50ms) where the overhead is noise."""
    for _ in range(warmup):
        out = fn(*args)
    _host_sync(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _host_sync(out)  # in-order device stream => all reps done
    return (time.perf_counter() - t0) / reps


def timeit_scan(op, init, iters=ITERS):
    """Device-side loop timing: op (carry -> same-shaped carry) runs `iters`
    times inside ONE jitted scan, so per-dispatch overhead divides out."""
    f = jax.jit(
        lambda c: jax.lax.scan(lambda c, _: (op(c), None), c, None,
                               length=iters)[0])
    _host_sync(f(init))  # compile + warm
    t0 = time.perf_counter()
    _host_sync(f(init))
    return (time.perf_counter() - t0) / iters


def report(probe, dt, flops, peak, extra=None):
    tf = flops / dt / 1e12
    eff = flops / dt / peak
    line = {
        "probe": probe,
        "ms": round(dt * 1e3, 3),
        "tflops": round(tf, 1),
        "eff_vs_peak": round(eff, 3),
    }
    if eff > 1.1:
        # physically impossible — the timed loop did not synchronize
        line["invalid"] = "eff>110% of peak: timing not synchronized, discard"
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    return line


def _keep_live(primary, *rest):
    """Fold scalars of auxiliary outputs into the carry so XLA cannot DCE
    the work that produced them (cost: one scalar add per aux)."""
    s = sum(r.sum().astype(jnp.float32) for r in rest)
    return primary + (s * 1e-30).astype(primary.dtype)


def main():
    cfg_name = sys.argv[1] if len(sys.argv) > 1 else "gpt3_125m"
    backend = jax.default_backend()
    print(json.dumps({"probe": "backend", "name": backend,
                      "device": str(getattr(jax.devices()[0], "device_kind", ""))}),
          flush=True)
    from bench import _peak_flops

    peak, kind = _peak_flops(jax.devices()[0])
    if backend == "cpu":
        peak = 1e12  # nominal, so the script still runs for smoke

    B, S = (8, 2048) if backend != "cpu" else (2, 256)
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt3_125m, gpt3_350m, GPTForCausalLM, GPTPretrainingCriterion

    if backend == "cpu":
        from paddle_tpu.models import gpt3_tiny

        cfg = gpt3_tiny()
        cfg.max_position_embeddings = S
    else:
        cfg = {"gpt3_125m": gpt3_125m, "gpt3_350m": gpt3_350m}[cfg_name](
            max_position_embeddings=S)
    H, L, nh, D = cfg.hidden_size, cfg.num_layers, cfg.num_heads, cfg.head_dim
    V = cfg.vocab_size
    key = jax.random.PRNGKey(0)

    # 0. per-dispatch overhead floor (the number the scan method removes)
    tiny = jnp.zeros((8,), jnp.float32)
    f_id = jax.jit(lambda x: x + 1.0)
    dt = timeit_wall(f_id, tiny, reps=10, warmup=3)
    print(json.dumps({"probe": "dispatch_overhead", "ms": round(dt * 1e3, 3)}),
          flush=True)

    # 1. matmul peak sanity: can this chip/tunnel hit its spec at all?
    for n in ((4096, 8192) if backend != "cpu" else (512,)):
        a = jax.random.normal(key, (n, n), jnp.bfloat16)
        scale = jnp.bfloat16(1.0 / math.sqrt(n))
        dt = timeit_scan(lambda c: (c @ a) * scale, a)
        report(f"matmul_bf16_{n}", dt, 2.0 * n ** 3, peak)

    # 2. qkv+out projection shape ([BS,H]@[H,H]), fwd and fwd+bwd
    x = jax.random.normal(key, (B * S, H), jnp.bfloat16)
    wq = jax.random.normal(key, (H, H), jnp.bfloat16) / math.sqrt(H)
    wo = jax.random.normal(key, (H, H), jnp.bfloat16) / math.sqrt(H)

    def proj2(c):
        return (c @ wq) @ wo

    dt = timeit_scan(proj2, x)
    proj_fwd = report("proj2_fwd", dt, 2 * 2 * B * S * H * H, peak)
    gp = jax.grad(lambda c, a_, b_: ((c @ a_) @ b_).astype(jnp.float32).sum(),
                  argnums=(0, 1, 2))

    def proj2_bwd(c):
        dx, dwa, dwb = gp(c, wq, wo)
        return _keep_live(dx, dwa, dwb)

    dt = timeit_scan(proj2_bwd, x)
    proj_bwd = report("proj2_fwdbwd", dt, 3 * 2 * 2 * B * S * H * H, peak)

    # 3. MLP-shaped matmul chain (the non-attention compute shape)
    w1 = jax.random.normal(key, (H, 4 * H), jnp.bfloat16) / math.sqrt(H)
    w2 = jax.random.normal(key, (4 * H, H), jnp.bfloat16) / math.sqrt(4 * H)

    def mlp(c, a_, b_):
        return jax.nn.gelu(c @ a_) @ b_

    dt = timeit_scan(lambda c: mlp(c, w1, w2), x)
    report("mlp_fwd", dt, 2 * 2 * B * S * H * 4 * H, peak)

    gm = jax.grad(lambda c, a_, b_: mlp(c, a_, b_).astype(jnp.float32).sum(),
                  argnums=(0, 1, 2))

    def mlp_bwd(c):
        dx, dw1, dw2 = gm(c, w1, w2)
        return _keep_live(dx, dw1, dw2)

    dt = timeit_scan(mlp_bwd, x)
    mlp_bwd_line = report("mlp_fwdbwd", dt, 3 * 2 * 2 * B * S * H * 4 * H, peak)

    # 4. attention: Pallas kernel vs XLA composite, fwd and fwd+bwd
    attn_flops_fwd = 2 * 2 * B * nh * S * S * D  # qk + pv (causal halves it)
    q = jax.random.normal(key, (B, S, nh, D), jnp.bfloat16)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention_fwd
    from paddle_tpu.nn.functional.flash_attention import _ref_attention

    def pal(c):
        return flash_attention_fwd(c, c, c, causal=True)

    def comp(c):
        return _ref_attention(c, c, c, causal=True)

    ab = {}
    for name, fn in (("attn_pallas", pal), ("attn_xla", comp)):
        try:
            dt = timeit_scan(fn, q)
            ab[name + "_fwd"] = report(name + "_fwd", dt, attn_flops_fwd / 2, peak)
            gfn = jax.grad(lambda c: fn(c).astype(jnp.float32).sum())
            dt = timeit_scan(gfn, q)
            ab[name + "_fwdbwd"] = report(
                name + "_fwdbwd", dt, attn_flops_fwd / 2 * 3.5, peak)
        except Exception as e:
            print(json.dumps({"probe": name, "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
    if "attn_pallas_fwdbwd" in ab and "attn_xla_fwdbwd" in ab:
        print(json.dumps({
            "probe": "attn_ab_verdict",
            "winner": ("pallas" if ab["attn_pallas_fwdbwd"]["ms"]
                       <= ab["attn_xla_fwdbwd"]["ms"] else "xla"),
            "pallas_ms": ab["attn_pallas_fwdbwd"]["ms"],
            "xla_ms": ab["attn_xla_fwdbwd"]["ms"],
        }), flush=True)

    # 5. lm head + cross entropy (tied-embedding shape)
    h = jax.random.normal(key, (B, S, H), jnp.bfloat16)
    w = jax.random.normal(key, (V, H), jnp.bfloat16) / math.sqrt(H)
    lab = jax.random.randint(key, (B, S), 0, V)

    def head_ce(h, w, lab):
        logits = h @ w.T
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, lab[..., None], axis=-1).mean()

    dt = timeit_scan(
        lambda c: _keep_live(c, head_ce(c, w, lab)[None]), h, iters=5)
    head_fwd = report("head_ce_fwd", dt, 2 * B * S * H * V, peak)
    gh = jax.grad(head_ce, argnums=(0, 1))

    def head_bwd(c):
        dh, dw = gh(c, w, lab)
        return _keep_live(dh, dw)

    dt = timeit_scan(head_bwd, h, iters=5)
    head_bwd_line = report("head_ce_fwdbwd", dt, 3 * 2 * B * S * H * V, peak)

    # 6. full model fwd and full train step (wall-clock: >=50ms, overhead ok)
    paddle.seed(0)
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())
    mesh = dist.build_mesh(devices=jax.devices()[:1])
    step = dist.DistributedTrainStep(
        model, lambda lg, lb: crit(lg, lb), optimizer, mesh=mesh,
        amp_level="O2", amp_dtype="bfloat16")
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, V, (B, S)))
    labels = paddle.to_tensor(rng.integers(0, V, (B, S)))

    n_params = cfg.num_params(include_embeddings=False) + V * H
    tok = B * S
    step_flops = 6.0 * n_params * tok + 12.0 * L * H * S * tok

    dt_step = timeit_wall(lambda: step(ids, labels)._value, reps=5, warmup=2)
    report("train_step", dt_step, step_flops, peak)

    # 7. eval (fwd-only) pass through the same machinery
    dt = timeit_wall(lambda: step.evaluate(ids, labels)._value, reps=5, warmup=2)
    report("eval_fwd", dt, step_flops / 3.0, peak)

    # 8. do the components sum to ~the step? (sanity on the attribution)
    # per decoder layer fwd+bwd: qkvo (4 HxH matmuls = 2x proj2's pair) +
    # MLP + attention — keyed to the kernel the model ACTUALLY selects
    from paddle_tpu.nn.functional.flash_attention import _use_pallas_kernel

    attn_key = ("attn_pallas_fwdbwd" if _use_pallas_kernel()
                else "attn_xla_fwdbwd")
    if attn_key not in ab and ab:
        attn_key = next(iter(k for k in ab if k.endswith("fwdbwd")), None)
    if attn_key in ab:
        per_layer_ms = (2.0 * proj_bwd["ms"] + mlp_bwd_line["ms"]
                        + ab[attn_key]["ms"])
        comp_ms = L * per_layer_ms + head_bwd_line["ms"]
        cov = comp_ms / (dt_step * 1e3)
        line = {
            "probe": "components_sum",
            "layers_x_perlayer_plus_head_ms": round(comp_ms, 1),
            "train_step_ms": round(dt_step * 1e3, 1),
            "coverage": round(cov, 3),
        }
        # isolated probes cannot overlap with neighbors the way the fused
        # step does, so coverage > 1 is expected; far outside [0.7, 1.3]
        # means the attribution is not trustworthy for ranking components
        if not 0.7 <= cov <= 1.3:
            line["note"] = ("coverage outside [0.7, 1.3]: isolated-probe "
                            "attribution unreliable for this run")
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
