"""Predicted-vs-measured tables for the mesh planner (docs/PLANNER.md).

Reads a Recorder history CSV written by `plan_and_tune` / `tune` —
`store_history()` rows carrying `predicted_step_time`, `step_time`,
`prediction_error_pct`, `pruned` — and/or a MeshPlan JSON artifact, and
prints:

* the per-trial table (predicted vs measured, signed error %),
* the ranking agreement: the measured top-1's analytic rank and whether it
  sits inside the analytic top-K (the planner's falsifiability check),
* pruned/skipped configs with their reasons,
* the plan artifact's mesh + cost breakdown when --plan is given.

Usage:
    python -m tools.plan_report history.csv [--plan mesh_plan.json]
                                            [--top-k 5] [--json]

Exit codes: 0 report printed, 2 unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _mesh_key(row):
    return (f"dp{row.get('dp_degree')}xpp{row.get('pp_degree')}"
            f"xsharding{row.get('sharding_degree')}xmp{row.get('mp_degree')}"
            f"/mbs{row.get('micro_batch_size')}"
            f"{'+rc' if row.get('use_recompute') else ''}")


def build_report(history, top_k=5):
    """Pure core (tests drive this): history rows -> report dict."""
    measured = [h for h in history
                if h.get("step_time") and not h.get("error")]
    errored = [h for h in history if h.get("error")]
    pruned = [h for h in history if h.get("pruned")]
    trials = []
    for h in sorted(measured, key=lambda r: r["step_time"]):
        t = {
            "mesh": _mesh_key(h),
            "measured_s": round(float(h["step_time"]), 6),
            "predicted_s": (None if h.get("predicted_step_time") is None
                            else round(float(h["predicted_step_time"]), 6)),
            "error_pct": h.get("prediction_error_pct"),
        }
        if (t["error_pct"] is None and t["predicted_s"] is not None
                and t["measured_s"]):
            t["error_pct"] = round(
                (t["predicted_s"] - t["measured_s"]) / t["measured_s"] * 100,
                2)
        trials.append(t)
    report = {
        "measured_trials": len(measured),
        "errored_trials": len(errored),
        "pruned_configs": len(pruned),
        "trials": trials,
        "pruned": [{"mesh": _mesh_key(h), "reason": h["pruned"]}
                   for h in pruned],
        "errors": [{"mesh": _mesh_key(h), "error": h["error"]}
                   for h in errored],
    }
    with_pred = [t for t in trials if t["predicted_s"] is not None]
    if with_pred:
        errs = [abs(t["error_pct"]) for t in with_pred
                if t["error_pct"] is not None]
        # the analytic ordering must cover the WHOLE ranked grid, not just
        # the measured shortlist — plan_and_tune records the rejected
        # candidates' predictions in their pruned rows, and without them
        # the measured best could never rank outside the top-K (the check
        # would be unfalsifiable, the one thing it must not be)
        all_pred = {}
        for h in history:
            if h.get("predicted_step_time") is not None:
                all_pred.setdefault(_mesh_key(h),
                                    float(h["predicted_step_time"]))
        analytic_rank = {m: i + 1 for i, (m, _p) in enumerate(
            sorted(all_pred.items(), key=lambda kv: kv[1]))}
        best = trials[0]  # sorted by measured time
        rank = analytic_rank.get(best["mesh"])
        report["calibration"] = {
            "mean_abs_error_pct": round(sum(errs) / len(errs), 2)
            if errs else None,
            "max_abs_error_pct": round(max(errs), 2) if errs else None,
            "measured_best": best["mesh"],
            "measured_best_analytic_rank": rank,
            "top_k": top_k,
            "measured_best_in_analytic_top_k": (rank is not None
                                                and rank <= top_k),
        }
    return report


def _print_human(report, plan=None):
    print(f"measured trials: {report['measured_trials']}   "
          f"errored: {report['errored_trials']}   "
          f"pruned: {report['pruned_configs']}")
    if report["trials"]:
        w = max(len(t["mesh"]) for t in report["trials"]) + 2
        print(f"\n{'mesh'.ljust(w)}{'measured_s':>12}{'predicted_s':>13}"
              f"{'error_%':>9}")
        for t in report["trials"]:
            pred = "-" if t["predicted_s"] is None else f"{t['predicted_s']:.6f}"
            err = "-" if t["error_pct"] is None else f"{t['error_pct']:+.1f}"
            print(f"{t['mesh'].ljust(w)}{t['measured_s']:>12.6f}"
                  f"{pred:>13}{err:>9}")
    cal = report.get("calibration")
    if cal:
        hit = "IN" if cal["measured_best_in_analytic_top_k"] else "OUTSIDE"
        print(f"\nmeasured best {cal['measured_best']} is analytic rank "
              f"#{cal['measured_best_analytic_rank']} — {hit} the "
              f"analytic top-{cal['top_k']}")
        if cal["mean_abs_error_pct"] is not None:
            print(f"prediction error: mean |{cal['mean_abs_error_pct']}|% "
                  f"max |{cal['max_abs_error_pct']}|%")
    if report["errors"]:
        print("\nerrored trials:")
        for e in report["errors"]:
            print(f"  {e['mesh']}: {e['error']}")
    if report["pruned"]:
        print("\npruned (never measured):")
        for p in report["pruned"]:
            print(f"  {p['mesh']}: {p['reason']}")
    if plan is not None:
        print(f"\nplan artifact: {plan.describe()}")
        cost = plan.cost
        print(f"  compute {cost.get('compute_s')}s + bubble "
              f"{cost.get('bubble_s')}s + exposed comm "
              f"{cost.get('exposed_comm_s')}s "
              f"(overlap {cost.get('overlap_fraction')} from "
              f"{cost.get('overlap_source')})")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m tools.plan_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("history", nargs="?",
                    help="Recorder history CSV (store_history output)")
    ap.add_argument("--plan", help="MeshPlan JSON artifact to summarize")
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    if not args.history and not args.plan:
        ap.print_usage(sys.stderr)
        return 2

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    history = []
    if args.history:
        from paddle_tpu.distributed.auto_tuner import Recorder

        history, missing = Recorder().load_history(args.history)
        if missing:
            print(f"plan_report: {args.history} not found", file=sys.stderr)
            return 2
    plan = None
    if args.plan:
        from paddle_tpu.distributed.planner import MeshPlan

        try:
            plan = MeshPlan.load(args.plan)
        except (OSError, ValueError, KeyError, TypeError) as e:
            print(f"plan_report: cannot read {args.plan}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2

    report = build_report(history, top_k=args.top_k)
    if plan is not None:
        report["plan"] = plan.to_dict()
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        _print_human(report, plan)
    return 0


if __name__ == "__main__":
    sys.exit(main())
