#!/bin/bash
# First-hardware-contact session: run EVERYTHING that needs the real TPU, in
# priority order, saving artifacts. Run the moment `jax.devices()` stops
# hanging (the axon tunnel wedged through rounds 2-3; bench early — a number
# in hand beats an optimization unmeasured).
#
#   bash tools/hw_session.sh [outdir]
#
# Order matters: (1) capture a baseline bench number BEFORE anything else,
# (2) validate the round-3 512-block Pallas kernels on Mosaic (interpret mode
# hid layout bugs in round 2), (3) profile to attribute the 1/MFU budget,
# (4) the BASELINE.md matrix, (5) autotuned rerun.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-hw_artifacts}"
mkdir -p "$OUT"
log() { echo "=== $* ==="; }

log "0. tunnel probe"
if ! timeout 120 python -c "import jax; print(jax.devices())"; then
  echo "tunnel still wedged; aborting"; exit 1
fi

log "1. baseline bench (gpt3_125m) BEFORE any validation churn"
BENCH_CONFIG=gpt3_125m timeout 1800 python bench.py | tee "$OUT/bench_125m.json"

log "2. Pallas kernel validation on real Mosaic (kT layout + key-bias paths)"
PADDLE_TPU_HW=1 timeout 2400 python -m pytest tests/test_pallas_kernels.py tests/test_masked_flash.py -x -q \
  2>&1 | tee "$OUT/kernel_validation.txt" | tail -5
echo "kernel validation rc=${PIPESTATUS[0]}" | tee -a "$OUT/kernel_validation.txt"

log "2b. attention kernel A/B (ours-vs-jax-reference-vs-composite, block sweep)"
timeout 2400 python tools/attn_ab.py | tee "$OUT/attn_ab.json"

log "3. per-component perf breakdown"
timeout 2400 python tools/perf_breakdown.py gpt3_125m | tee "$OUT/breakdown_125m.json"

log "4. bench ladder + matrix"
timeout 1800 python bench.py | tee "$OUT/bench_ladder.json"
BENCH_MATRIX=1 timeout 3600 python bench.py | tee "$OUT/bench_matrix.json"

log "5. autotuned rerun (block-size search on chip)"
PADDLE_TPU_AUTOTUNE=1 BENCH_CONFIG=gpt3_125m timeout 2400 python bench.py \
  | tee "$OUT/bench_125m_autotuned.json"

log "5b. A/B: XLA-composite attention + exact online-softmax kernel"
BENCH_NO_PALLAS=1 BENCH_CONFIG=gpt3_125m timeout 1800 python bench.py \
  | tee "$OUT/bench_125m_no_pallas.json"
PADDLE_TPU_FLASH_SAFE_SOFTMAX=1 BENCH_CONFIG=gpt3_125m timeout 1800 python bench.py \
  | tee "$OUT/bench_125m_safe_softmax.json"

log "6. trace for the judge (BENCH_TRACE_DIR)"
BENCH_TRACE_DIR="$OUT/trace" BENCH_CONFIG=gpt3_125m timeout 1800 python bench.py \
  | tee "$OUT/bench_125m_traced.json"

log "7. round-4 additions: decode/serving throughput + RNN scan on chip"
timeout 1200 python tools/decode_bench.py | tee "$OUT/decode_bench.json"
PADDLE_TPU_HW=1 timeout 1200 python -m pytest tests/test_rnn.py -q -k "scan or parity" \
  2>&1 | tail -3 | tee "$OUT/rnn_on_tpu.txt"

log "done — artifacts in $OUT/"
ls -la "$OUT"
