#!/usr/bin/env python
"""Fault-injection harness for the resilience stack (docs/RESILIENCE.md).

Two layers:

1. **On-disk faults** (usable against any checkpoint, no framework import):
   `corrupt_file` flips bytes mid-file, `truncate_file` cuts it short —
   simulating bit rot and torn writes respectively.

2. **In-process fault points** (paddle_tpu/distributed/faults.py): arm via
   PADDLE_FAULT_INJECT="point:action[:arg][@n]" to kill/raise/stall at the
   exact instants a real failure lands:

       ckpt.before_shards    save started, nothing written
       ckpt.mid_save         shards on disk, no metadata
       ckpt.before_commit    metadata written, no COMMIT marker
       ckpt.before_rename    committed tmp dir, not yet visible
       trainer.before_step   start of a train step (sleep => watchdog hang)

CLI:
    python tools/fault_inject.py --corrupt  CKPT_DIR_OR_FILE [--nbytes 8]
    python tools/fault_inject.py --truncate CKPT_DIR_OR_FILE [--frac 0.5]
    python tools/fault_inject.py --self-test       # harness verifies itself
    python tools/fault_inject.py --list-points

The pytest fixture `fault_injector` (tests/conftest.py) wraps all of this
for tests. `--self-test` runs the corruption round-trip end to end (save →
corrupt → checksum rejection → fallback; interrupted save → discovery skips
the partial) so the harness itself is exercised, not assumed.
"""

from __future__ import annotations

import argparse
import glob
import os
import random
import sys

POINTS = [
    ("ckpt.before_shards", "save started, nothing written yet"),
    ("ckpt.mid_save", "shard files on disk, metadata absent"),
    ("ckpt.before_commit", "metadata written, COMMIT marker absent"),
    ("ckpt.before_rename", "committed tmp dir, final rename pending"),
    ("trainer.before_step", "inside a ResilientTrainer step's watchdog region"),
]


def _pick_shard(target):
    """A .distcp path: the file itself, or one inside a checkpoint dir."""
    if os.path.isdir(target):
        shards = sorted(glob.glob(os.path.join(target, "*.distcp")))
        if not shards:
            raise FileNotFoundError(f"no .distcp shard files under {target}")
        return shards[0]
    return target


def corrupt_file(target, nbytes=8, seed=0):
    """Flip `nbytes` random bytes mid-file (bit rot / bad DMA). Returns the
    path actually corrupted."""
    path = _pick_shard(target)
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        raise ValueError(f"{path} is empty, nothing to corrupt")
    rng = random.Random(seed)
    for _ in range(max(1, nbytes)):
        i = rng.randrange(len(data))
        data[i] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(data))
    return path


def truncate_file(target, frac=0.5):
    """Cut the file to `frac` of its size (torn write / dead host mid-flush).
    Returns the path actually truncated."""
    path = _pick_shard(target)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(0, int(size * frac)))
    return path


# --------------------------------------------------------------------------- #
# self-test: the harness proving it can make the checkpoint layer fail
# --------------------------------------------------------------------------- #

def self_test():
    import tempfile

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.distributed import faults
    from paddle_tpu.distributed.checkpoint import (
        CheckpointCorruptError,
        CheckpointManager,
        latest_checkpoint,
        load_state_dict,
    )

    failures = []

    def check(name, cond):
        print(f"  [{'ok' if cond else 'FAIL'}] {name}")
        if not cond:
            failures.append(name)

    root = tempfile.mkdtemp(prefix="fi_selftest_")
    mgr = CheckpointManager(root, keep_last_n=4)
    sd = {"w": paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(4, 6))}
    mgr.save(sd, 1)
    mgr.save(sd, 2)
    check("two committed checkpoints", latest_checkpoint(root).step == 2)

    # corruption round-trip: flip bytes -> load raises naming the file,
    # discovery falls back to step 1
    bad = corrupt_file(mgr.path_for(2))
    try:
        load_state_dict({"w": paddle.to_tensor(np.zeros((4, 6), np.float32))},
                        mgr.path_for(2))
        check("corrupt load raises", False)
    except CheckpointCorruptError as e:
        check("corrupt load raises naming the file",
              os.path.basename(bad) in str(e))
    check("discovery falls back past corruption",
          latest_checkpoint(root).step == 1)

    # truncation round-trip
    mgr.save(sd, 3)
    truncate_file(mgr.path_for(3), frac=0.3)
    try:
        load_state_dict({"w": paddle.to_tensor(np.zeros((4, 6), np.float32))},
                        mgr.path_for(3))
        check("truncated load raises", False)
    except CheckpointCorruptError:
        check("truncated load raises", True)
    check("discovery falls back past truncation",
          latest_checkpoint(root).step == 1)

    # interrupted save (in-process exc at the commit boundary): tmp dir
    # left behind, discovery ignores it, next save sweeps it
    os.environ["PADDLE_FAULT_INJECT"] = "ckpt.before_commit:exc"
    try:
        try:
            mgr.save(sd, 4)
            check("armed fault point trips", False)
        except faults.FaultInjected:
            check("armed fault point trips", True)
    finally:
        del os.environ["PADDLE_FAULT_INJECT"]
    check("interrupted save leaves only a .tmp",
          not os.path.isdir(mgr.path_for(4))
          and os.path.isdir(mgr.path_for(4) + ".tmp"))
    check("discovery ignores the partial save",
          latest_checkpoint(root).step == 1)
    mgr.save(sd, 5)
    check("next save sweeps the stale .tmp",
          not os.path.isdir(mgr.path_for(4) + ".tmp"))
    check("recovery proceeds past it", latest_checkpoint(root).step == 5)

    if failures:
        print(f"self-test FAILED: {failures}")
        return 1
    print("self-test passed")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--corrupt", metavar="PATH",
                   help="flip bytes in a shard file (or first shard of a dir)")
    p.add_argument("--truncate", metavar="PATH",
                   help="truncate a shard file (or first shard of a dir)")
    p.add_argument("--nbytes", type=int, default=8)
    p.add_argument("--frac", type=float, default=0.5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--list-points", action="store_true")
    p.add_argument("--self-test", action="store_true",
                   help="verify the harness against the checkpoint layer")
    args = p.parse_args(argv)
    if args.list_points:
        for name, desc in POINTS:
            print(f"{name:24s} {desc}")
        return 0
    if args.self_test:
        return self_test()
    if args.corrupt:
        print(f"corrupted: {corrupt_file(args.corrupt, args.nbytes, args.seed)}")
        return 0
    if args.truncate:
        print(f"truncated: {truncate_file(args.truncate, args.frac)}")
        return 0
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
