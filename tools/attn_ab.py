"""Attention kernel A/B on the current backend (meant for TPU).

Compares, at the GPT-125M training shape (and optional others):
  - this repo's Pallas flash kernel at several (bq, bk) block sizes
  - jax.experimental.pallas.ops.tpu.flash_attention (the JAX team's tuned
    TPU kernel) as the achievable-performance oracle
  - the XLA composite (_ref_attention)

Timing: device-side lax.scan loops (see tools/perf_breakdown.py) so the
axon tunnel's per-dispatch overhead divides out.

Usage: python tools/attn_ab.py [B] [S] [H] [D]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 20


def _host_sync(out):
    leaf = jax.tree.leaves(out)[0]
    one = leaf.ravel()[0:1] if getattr(leaf, "ndim", 0) else leaf
    return np.asarray(jax.device_get(one))


def timeit_scan(op, init, iters=ITERS):
    f = jax.jit(lambda c: jax.lax.scan(lambda c, _: (op(c), None), c, None,
                                       length=iters)[0])
    _host_sync(f(init))
    t0 = time.perf_counter()
    _host_sync(f(init))
    return (time.perf_counter() - t0) / iters


def main():
    argv = sys.argv[1:]
    B = int(argv[0]) if len(argv) > 0 else 8
    S = int(argv[1]) if len(argv) > 1 else 2048
    H = int(argv[2]) if len(argv) > 2 else 12
    D = int(argv[3]) if len(argv) > 3 else 64
    key = jax.random.PRNGKey(0)
    scale = 1.0 / (D ** 0.5)
    flops_fwd = 2 * 2 * B * H * S * S * D / 2  # causal
    q = jax.random.normal(key, (B, H, S, D), jnp.bfloat16)  # [B,H,S,D]

    print(json.dumps({"probe": "shape", "B": B, "S": S, "H": H, "D": D,
                      "backend": jax.default_backend()}), flush=True)

    def emit(name, dt, mult=1.0):
        print(json.dumps({
            "probe": name, "ms": round(dt * 1e3, 3),
            "tflops": round(flops_fwd * mult / dt / 1e12, 1),
        }), flush=True)

    # ---- ours at several block sizes ----
    from paddle_tpu.ops.pallas.flash_attention import _flash

    for blk in ((512, 512), (256, 512), (512, 1024), (256, 256),
                (128, 512), (128, 128), (1024, 1024)):
        bq, bk = blk
        if bq > S or bk > S:
            continue
        try:
            fwd = lambda c: _flash(c, c, c, True, scale, bq, bk)
            dt = timeit_scan(fwd, q)
            emit(f"ours_fwd_{bq}x{bk}", dt)
            g = jax.grad(lambda c: _flash(c, c, c, True, scale, bq, bk)
                         .astype(jnp.float32).sum())
            dt = timeit_scan(g, q)
            emit(f"ours_fwdbwd_{bq}x{bk}", dt, 3.5)
        except Exception as e:
            print(json.dumps({"probe": f"ours_{bq}x{bk}",
                              "error": f"{type(e).__name__}: {e}"[:160]}),
                  flush=True)

    # ---- jax reference TPU kernel (oracle) ----
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jax_flash)

        fwd = lambda c: jax_flash(c, c, c, causal=True, sm_scale=scale)
        dt = timeit_scan(fwd, q)
        emit("jaxref_fwd_default", dt)
        g = jax.grad(lambda c: jax_flash(c, c, c, causal=True, sm_scale=scale)
                     .astype(jnp.float32).sum())
        dt = timeit_scan(g, q)
        emit("jaxref_fwdbwd_default", dt, 3.5)
    except Exception as e:
        print(json.dumps({"probe": "jaxref",
                          "error": f"{type(e).__name__}: {e}"[:200]}),
              flush=True)

    # ---- XLA composite ----
    from paddle_tpu.nn.functional.flash_attention import _ref_attention

    comp = lambda c: jnp.swapaxes(
        _ref_attention(jnp.swapaxes(c, 1, 2), jnp.swapaxes(c, 1, 2),
                       jnp.swapaxes(c, 1, 2), causal=True), 1, 2)
    dt = timeit_scan(comp, q)
    emit("xla_fwd", dt)


if __name__ == "__main__":
    main()
