"""Per-step comm/compute overlap report from step-timeline JSONL.

Reads the records `bench.py --emit-metrics` / `enable_step_timeline(
jsonl_path=...)` append (one JSON object per training step) and prints the
overlap picture the scheduling work targets: per-step `overlap_fraction`,
the comm/covered/exposed interval-union seconds behind it, and which comm
regions the exposed time belongs to.

    python -m tools.overlap_report bench_metrics.jsonl
    python -m tools.overlap_report steps.jsonl --rung gpt3_125m --per-step
    python -m tools.overlap_report before.jsonl after.jsonl   # A/B delta

Records written before the overlap field existed are re-derived from their
interval lists when possible (`spans.overlap_stats` is pure), so old JSONL
still reports.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.observability.spans import (  # noqa: E402
    COMM_KINDS,
    _intersect_len,
    _merge_intervals,
    aggregate_overlap,
    overlap_stats,
)


def load_records(path, rung=None):
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "comm_tasks" not in rec and "overlap" not in rec:
                continue  # not a step record (e.g. metric export lines)
            if rung and rec.get("rung") != rung:
                continue
            recs.append(rec)
    return recs


def record_overlap(rec):
    ov = rec.get("overlap")
    if ov is None:
        ov = overlap_stats(rec.get("comm_tasks", []), rec.get("spans", []))
    return ov


def exposed_by_desc(rec):
    """Exposed seconds per comm_task desc: each comm region's own interval
    minus its intersection with the step's compute-span union."""
    compute = _merge_intervals(
        (s.get("start_ns", 0) / 1e9,
         s.get("start_ns", 0) / 1e9 + s.get("dur_s", 0.0))
        for s in rec.get("spans", [])
        if (s.get("attrs") or {}).get("kind") == "compute")
    out = {}
    for t in rec.get("comm_tasks", []):
        if t.get("kind", "comm") not in COMM_KINDS:
            continue
        s = t.get("start_ns", 0) / 1e9
        iv = [(s, s + t.get("dur_s", 0.0))]
        exposed = t.get("dur_s", 0.0) - _intersect_len(iv, compute)
        if exposed > 0:
            out[t["desc"]] = out.get(t["desc"], 0.0) + exposed
    return out


def summarize(recs):
    ovs = [record_overlap(r) for r in recs]
    agg = aggregate_overlap(ovs)
    fracs = [o["fraction"] for o in ovs]
    by_desc = {}
    for r in recs:
        for desc, s in exposed_by_desc(r).items():
            by_desc[desc] = by_desc.get(desc, 0.0) + s
    return {
        "steps": len(recs),
        "overlap_fraction": round(agg["fraction"], 4),
        "fraction_min": round(min(fracs), 4) if fracs else 1.0,
        "fraction_mean": round(sum(fracs) / len(fracs), 4) if fracs else 1.0,
        "comm_s": agg["comm_s"],
        "covered_s": agg["covered_s"],
        "exposed_s": agg["exposed_s"],
        "exposed_by_desc": {
            k: round(v, 6)
            for k, v in sorted(by_desc.items(), key=lambda kv: -kv[1])
        },
    }


def print_summary(path, summary, top):
    print(f"== {path}: {summary['steps']} steps ==")
    print(f"  overlap_fraction {summary['overlap_fraction']:.4f} "
          f"(mean {summary['fraction_mean']:.4f}, "
          f"min {summary['fraction_min']:.4f})")
    print(f"  comm {summary['comm_s'] * 1e3:.3f} ms  "
          f"covered {summary['covered_s'] * 1e3:.3f} ms  "
          f"exposed {summary['exposed_s'] * 1e3:.3f} ms")
    items = list(summary["exposed_by_desc"].items())[:top]
    if items:
        print("  exposed comm by region:")
        for desc, s in items:
            print(f"    {desc:<32} {s * 1e3:10.3f} ms")


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="comm/compute overlap breakdown from step-timeline JSONL")
    ap.add_argument("paths", nargs="+", help="step-timeline JSONL file(s); "
                    "two files print an A/B delta")
    ap.add_argument("--rung", help="only records tagged with this bench rung")
    ap.add_argument("--per-step", action="store_true",
                    help="one line per step record")
    ap.add_argument("--top", type=int, default=8,
                    help="exposed-comm regions to list (default 8)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: one summary JSON line per file")
    args = ap.parse_args(argv)

    summaries = []
    for path in args.paths:
        recs = load_records(path, rung=args.rung)
        if not recs:
            print(f"== {path}: no step records"
                  + (f" for rung {args.rung!r}" if args.rung else ""),
                  file=sys.stderr)
            summaries.append(None)
            continue
        s = summarize(recs)
        summaries.append(s)
        if args.json:
            print(json.dumps({"path": path, **s}, sort_keys=True))
        else:
            print_summary(path, s, args.top)
            if args.per_step:
                for r in recs:
                    ov = record_overlap(r)
                    tag = f" rung={r['rung']}" if r.get("rung") else ""
                    print(f"  step {r.get('step', '?'):>4}{tag} "
                          f"dur {r.get('dur_s', 0) * 1e3:8.3f} ms  "
                          f"overlap {ov['fraction']:.4f}  "
                          f"exposed {ov['exposed_s'] * 1e3:8.3f} ms")
    if len(args.paths) == 2 and all(summaries):
        a, b = summaries
        print(f"== delta ({args.paths[1]} vs {args.paths[0]}) ==")
        print(f"  overlap_fraction {a['overlap_fraction']:.4f} -> "
              f"{b['overlap_fraction']:.4f} "
              f"({b['overlap_fraction'] - a['overlap_fraction']:+.4f})")
        print(f"  exposed per step {a['exposed_s'] / max(a['steps'], 1) * 1e3:.3f}"
              f" -> {b['exposed_s'] / max(b['steps'], 1) * 1e3:.3f} ms")
    return 0 if any(summaries) else 1


if __name__ == "__main__":
    sys.exit(main())
