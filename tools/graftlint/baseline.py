"""Baseline bookkeeping: track pre-existing violations without blocking.

A baseline is a JSON multiset of finding fingerprints — (rule, path, stripped
source line), deliberately line-number-free so edits elsewhere in a file do
not invalidate entries. The contract:

- a finding whose fingerprint count is within the baseline is *known* (shown
  only with --show-baselined, never fails the run);
- a finding beyond its baselined count is *new* and fails the run (exit 1);
- fixing a violation then rewriting with --write-baseline shrinks the file —
  the ratchet only ever tightens unless someone deliberately regenerates.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Iterable, Sequence

from .engine import PARSE_ERROR_RULE, Finding

FORMAT_VERSION = 1


def aggregate(findings: Iterable[Finding]) -> Counter:
    return Counter(f.fingerprint for f in findings)


def save(path: Path | str, findings: Sequence[Finding]) -> None:
    # GL000 parse errors are never baselineable: their fingerprint carries no
    # snippet, so one baselined entry would absorb EVERY future parse error
    # in that file — a truncated checkout must always fail loudly
    counts = aggregate(f for f in findings if f.rule != PARSE_ERROR_RULE)
    payload = {
        "version": FORMAT_VERSION,
        "comment": "graftlint baseline — regenerate with --write-baseline; "
                   "entries are rule|path|source-line fingerprints",
        "entries": {k: counts[k] for k in sorted(counts)},
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n",
                          encoding="utf-8")


def load(path: Path | str) -> Counter:
    raw = json.loads(Path(path).read_text(encoding="utf-8"))
    if not isinstance(raw, dict) or "entries" not in raw:
        raise ValueError(f"{path}: not a graftlint baseline (missing 'entries')")
    if raw.get("version") != FORMAT_VERSION:
        raise ValueError(
            f"{path}: baseline format version {raw.get('version')!r} "
            f"unsupported (expected {FORMAT_VERSION})")
    entries = raw["entries"]
    bad = {k: v for k, v in entries.items()
           if not isinstance(v, int) or v < 1}
    if bad:
        raise ValueError(f"{path}: non-positive baseline counts: {sorted(bad)}")
    return Counter(entries)


def partition(findings: Sequence[Finding], baseline: Counter):
    """Split findings into (new, baselined).

    Within one fingerprint the *first* occurrences (file order) are treated
    as the baselined ones — arbitrary but stable, and irrelevant to exit
    status, which depends only on counts.
    """
    remaining = Counter(baseline)
    new: list[Finding] = []
    known: list[Finding] = []
    for f in findings:
        if f.rule != PARSE_ERROR_RULE and remaining[f.fingerprint] > 0:
            remaining[f.fingerprint] -= 1
            known.append(f)
        else:
            new.append(f)  # parse errors are always new, never baselined
    return new, known
