"""Runtime cross-check: catch *actual* host syncs under tracing.

GL001 is a static reachability over-approximation; this module is its dynamic
ground truth. It plugs into the two hook points framework/core.py already
exposes:

- the sync-observer chain (`add_sync_observer`) — fired by
  Tensor.__bool__/__int__/__float__/.numpy()/.item()/.tolist(), i.e. exactly
  the host-sync surface GL001 models. While `in_tracing()` is true the
  observer raises `HostSyncInTraceError` (mode "raise", the default) or emits
  a `GraftlintRuntimeWarning` (mode "warn"). Every sync (traced or not) also
  bumps `host_syncs_total`, which the observability StepTimeline must agree
  with on the same run (tests/test_observability.py).
- the op-input-interceptor chain (`add_op_input_interceptor`) — used to
  census op names dispatched under tracing, so the report shows *what ran
  traced* next to what synced.

The report also folds in `dispatch_cache_stats()` so a jit-blacklisted hot op
(`uncacheable_ops` — every call retraces eagerly) surfaces in the same output
as lint findings: both are "this op is silently slow" signals.

Activation: `GRAFTLINT_RUNTIME=1` (raise) or `GRAFTLINT_RUNTIME=warn` in the
environment — paddle_tpu/__init__.py installs the checks at import time when
the variable is set — or call `install_runtime_checks()` directly (tests).

Both hooks register through the chained add_*/remove_* API, so these checks
compose with amp autocast (which owns the base interceptor slot), the SOT
capture (which owns the base observer slot), and the observability
StepTimeline (a fellow chain entry) — enabling telemetry and
GRAFTLINT_RUNTIME=1 together drops nothing.
"""

from __future__ import annotations

import os
import warnings

__all__ = [
    "HostSyncInTraceError",
    "GraftlintRuntimeWarning",
    "install_runtime_checks",
    "uninstall_runtime_checks",
    "reset_runtime_events",
    "runtime_report",
    "format_report",
]


class HostSyncInTraceError(RuntimeError):
    """A concrete-value host sync executed while a jax trace was active."""


class GraftlintRuntimeWarning(RuntimeWarning):
    pass


_state = {
    "installed": False,
    "mode": "raise",
    "events": [],        # host syncs observed under tracing
    "op_census": {},     # op name -> calls dispatched while tracing
    "syncs_total": 0,    # every observed sync, traced or not
}


def _core():
    from paddle_tpu.framework import core

    return core


def _mode_from_env() -> str:
    raw = os.environ.get("GRAFTLINT_RUNTIME", "").strip().lower()
    return "warn" if raw == "warn" else "raise"


def _observer(kind, tensor):
    _state["syncs_total"] += 1
    if _core().in_tracing():
        shape = tuple(getattr(tensor, "shape", ()) or ())
        _state["events"].append({"kind": kind, "shape": shape})
        msg = (
            f"graftlint GL001 (runtime): host sync `{kind}` on a "
            f"tensor of shape {shape} while a jax trace is active — "
            "this concretizes the tracer (trace failure, or a silent "
            "per-step device round trip on fallback paths). Move the "
            "sync out of the traced region, or set GRAFTLINT_RUNTIME="
            "warn to only report."
        )
        if _state["mode"] == "raise":
            raise HostSyncInTraceError(msg)
        warnings.warn(msg, GraftlintRuntimeWarning, stacklevel=3)
    return None


def _interceptor(name, values):
    if _core().in_tracing():
        census = _state["op_census"]
        census[name] = census.get(name, 0) + 1
    return values


def install_runtime_checks(mode: str | None = None) -> None:
    """Idempotent; `mode` is "raise" (default) or "warn"."""
    core = _core()
    if _state["installed"]:
        _state["mode"] = mode or _state["mode"]
        return
    mode = mode or _mode_from_env()
    if mode not in ("raise", "warn"):
        raise ValueError(f"graftlint runtime mode must be 'raise'/'warn', got {mode!r}")
    _state["mode"] = mode
    core.add_sync_observer(_observer)
    core.add_op_input_interceptor(_interceptor)
    _state["installed"] = True


def uninstall_runtime_checks() -> None:
    if not _state["installed"]:
        return
    core = _core()
    core.remove_sync_observer(_observer)
    core.remove_op_input_interceptor(_interceptor)
    _state["installed"] = False


def reset_runtime_events() -> None:
    _state["events"].clear()
    _state["op_census"].clear()
    _state["syncs_total"] = 0


def runtime_report() -> dict:
    """Host syncs seen under tracing + the dispatch-cache health counters
    (cache_stats / uncacheable_ops surfaced next to lint findings, so a
    jit-blacklisted hot op reads as the perf bug it is)."""
    core = _core()
    stats = core.dispatch_cache_stats()
    return {
        "mode": _state["mode"] if _state["installed"] else None,
        "host_syncs_total": _state["syncs_total"],
        "host_syncs_in_trace": list(_state["events"]),
        "traced_op_census": dict(_state["op_census"]),
        "dispatch_cache": {k: stats[k] for k in ("hits", "misses", "bypass")},
        "uncacheable_ops": stats["uncacheable_ops"],
        "bypassed_ops": stats["bypassed_ops"],
    }


def format_report() -> str:
    rep = runtime_report()
    lines = ["graftlint runtime report",
             f"  host syncs observed: {rep['host_syncs_total']} "
             f"({len(rep['host_syncs_in_trace'])} under tracing)"]
    for e in rep["host_syncs_in_trace"][:20]:
        lines.append(f"    - {e['kind']} shape={e['shape']}")
    dc = rep["dispatch_cache"]
    lines.append(f"  dispatch cache: hits={dc['hits']} misses={dc['misses']} "
                 f"bypass={dc['bypass']}")
    if rep["uncacheable_ops"]:
        lines.append("  uncacheable ops (permanent per-call retrace — "
                     "GL004-class perf hazard at runtime):")
        for name in rep["uncacheable_ops"]:
            lines.append(f"    - {name}")
    hot = sorted(rep["bypassed_ops"].items(), key=lambda kv: -kv[1])[:10]
    if hot:
        lines.append("  hottest eager-bypassed ops:")
        for name, n in hot:
            lines.append(f"    - {name}: {n}")
    return "\n".join(lines)
