"""Runtime cross-check: catch *actual* host syncs under tracing.

GL001 is a static reachability over-approximation; this module is its dynamic
ground truth. It plugs into the two hook points framework/core.py already
exposes:

- `set_sync_observer` — fired by Tensor.__bool__/__int__/__float__/.numpy()/
  .item()/.tolist(), i.e. exactly the host-sync surface GL001 models. While
  `in_tracing()` is true the observer raises `HostSyncInTraceError` (mode
  "raise", the default) or emits a `GraftlintRuntimeWarning` (mode "warn").
- `set_op_input_interceptor` — used to census op names dispatched under
  tracing, so the report shows *what ran traced* next to what synced.

The report also folds in `dispatch_cache_stats()` so a jit-blacklisted hot op
(`uncacheable_ops` — every call retraces eagerly) surfaces in the same output
as lint findings: both are "this op is silently slow" signals.

Activation: `GRAFTLINT_RUNTIME=1` (raise) or `GRAFTLINT_RUNTIME=warn` in the
environment — paddle_tpu/__init__.py installs the checks at import time when
the variable is set — or call `install_runtime_checks()` directly (tests).

Caveat: both hooks are single-slot. The installer chains whatever observer /
interceptor was present, and sot.py's capture path save/restores around
itself, but amp's autocast *replaces* the interceptor — install runtime
checks first and the op census simply pauses while autocast is active; sync
enforcement (the part that matters) is unaffected.
"""

from __future__ import annotations

import os
import warnings

__all__ = [
    "HostSyncInTraceError",
    "GraftlintRuntimeWarning",
    "install_runtime_checks",
    "uninstall_runtime_checks",
    "reset_runtime_events",
    "runtime_report",
    "format_report",
]


class HostSyncInTraceError(RuntimeError):
    """A concrete-value host sync executed while a jax trace was active."""


class GraftlintRuntimeWarning(RuntimeWarning):
    pass


_state = {
    "installed": False,
    "mode": "raise",
    "prev_observer": None,
    "prev_interceptor": None,
    "events": [],        # host syncs observed under tracing
    "op_census": {},     # op name -> calls dispatched while tracing
}


def _core():
    from paddle_tpu.framework import core

    return core


def _mode_from_env() -> str:
    raw = os.environ.get("GRAFTLINT_RUNTIME", "").strip().lower()
    return "warn" if raw == "warn" else "raise"


def install_runtime_checks(mode: str | None = None) -> None:
    """Idempotent; `mode` is "raise" (default) or "warn"."""
    core = _core()
    if _state["installed"]:
        _state["mode"] = mode or _state["mode"]
        return
    mode = mode or _mode_from_env()
    if mode not in ("raise", "warn"):
        raise ValueError(f"graftlint runtime mode must be 'raise'/'warn', got {mode!r}")
    _state.update(mode=mode,
                  prev_observer=core._sync_observer,
                  prev_interceptor=core._op_input_interceptor)

    prev_obs = _state["prev_observer"]
    prev_icp = _state["prev_interceptor"]

    def _observer(kind, tensor):
        rep = prev_obs(kind, tensor) if prev_obs is not None else None
        if core.in_tracing():
            shape = tuple(getattr(tensor, "shape", ()) or ())
            _state["events"].append({"kind": kind, "shape": shape})
            msg = (
                f"graftlint GL001 (runtime): host sync `{kind}` on a "
                f"tensor of shape {shape} while a jax trace is active — "
                "this concretizes the tracer (trace failure, or a silent "
                "per-step device round trip on fallback paths). Move the "
                "sync out of the traced region, or set GRAFTLINT_RUNTIME="
                "warn to only report."
            )
            if _state["mode"] == "raise":
                raise HostSyncInTraceError(msg)
            warnings.warn(msg, GraftlintRuntimeWarning, stacklevel=3)
        return rep

    def _interceptor(name, values):
        if prev_icp is not None:
            values = prev_icp(name, values)
        if core.in_tracing():
            census = _state["op_census"]
            census[name] = census.get(name, 0) + 1
        return values

    core.set_sync_observer(_observer)
    core.set_op_input_interceptor(_interceptor)
    _state["installed"] = True


def uninstall_runtime_checks() -> None:
    if not _state["installed"]:
        return
    core = _core()
    core.set_sync_observer(_state["prev_observer"])
    core.set_op_input_interceptor(_state["prev_interceptor"])
    _state.update(installed=False, prev_observer=None, prev_interceptor=None)


def reset_runtime_events() -> None:
    _state["events"].clear()
    _state["op_census"].clear()


def runtime_report() -> dict:
    """Host syncs seen under tracing + the dispatch-cache health counters
    (cache_stats / uncacheable_ops surfaced next to lint findings, so a
    jit-blacklisted hot op reads as the perf bug it is)."""
    core = _core()
    stats = core.dispatch_cache_stats()
    return {
        "mode": _state["mode"] if _state["installed"] else None,
        "host_syncs_in_trace": list(_state["events"]),
        "traced_op_census": dict(_state["op_census"]),
        "dispatch_cache": {k: stats[k] for k in ("hits", "misses", "bypass")},
        "uncacheable_ops": stats["uncacheable_ops"],
        "bypassed_ops": stats["bypassed_ops"],
    }


def format_report() -> str:
    rep = runtime_report()
    lines = ["graftlint runtime report",
             f"  host syncs under tracing: {len(rep['host_syncs_in_trace'])}"]
    for e in rep["host_syncs_in_trace"][:20]:
        lines.append(f"    - {e['kind']} shape={e['shape']}")
    dc = rep["dispatch_cache"]
    lines.append(f"  dispatch cache: hits={dc['hits']} misses={dc['misses']} "
                 f"bypass={dc['bypass']}")
    if rep["uncacheable_ops"]:
        lines.append("  uncacheable ops (permanent per-call retrace — "
                     "GL004-class perf hazard at runtime):")
        for name in rep["uncacheable_ops"]:
            lines.append(f"    - {name}")
    hot = sorted(rep["bypassed_ops"].items(), key=lambda kv: -kv[1])[:10]
    if hot:
        lines.append("  hottest eager-bypassed ops:")
        for name, n in hot:
            lines.append(f"    - {name}: {n}")
    return "\n".join(lines)
