"""graftlint CLI.

    python -m tools.graftlint paddle_tpu --baseline tools/graftlint/baseline.json
    python -m tools.graftlint paddle_tpu --stats
    python -m tools.graftlint --list-rules

Exit codes (asserted by tests/test_graftlint.py):
    0  clean — no findings above the baseline
    1  new findings (or parse errors)
    2  internal error (bad arguments, unreadable baseline, linter crash)
"""

from __future__ import annotations

import argparse
import sys
import traceback
from collections import Counter
from pathlib import Path

from . import baseline as baseline_mod
from .engine import lint_paths
from .rules import RULES, get_rules

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="TPU/JAX-aware static analysis (rules GL001-GL006; "
                    "see docs/LINTING.md)")
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--baseline", metavar="FILE",
                   help="baseline JSON; findings within it do not fail the run")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="write current findings as the new baseline and exit 0")
    p.add_argument("--rules", metavar="IDS",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--stats", action="store_true",
                   help="print per-rule totals (total/new) instead of findings")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print findings covered by the baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--root", metavar="DIR", default=None,
                   help="directory paths are reported relative to (default: cwd)")
    return p


def run(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for rule in get_rules():
            print(f"{rule.id}  {rule.title}")
            print(f"       {rule.rationale}\n")
        return EXIT_CLEAN

    if not args.paths:
        print("graftlint: no paths given (try: python -m tools.graftlint "
              "paddle_tpu)", file=sys.stderr)
        return EXIT_INTERNAL

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rule_ids if r not in RULES]
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return EXIT_INTERNAL

    findings = lint_paths(args.paths, root=args.root, rules=rule_ids)

    if args.write_baseline:
        baseline_mod.save(args.write_baseline, findings)
        print(f"graftlint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}")
        return EXIT_CLEAN

    baseline = Counter()
    if args.baseline:
        baseline = baseline_mod.load(args.baseline)
    new, known = baseline_mod.partition(findings, baseline)

    if args.stats:
        totals = Counter(f.rule for f in findings)
        news = Counter(f.rule for f in new)
        for rule in sorted(set(totals) | set(RULES)):
            print(f"{rule} total={totals.get(rule, 0)} new={news.get(rule, 0)}")
        print(f"TOTAL total={len(findings)} new={len(new)}")
    else:
        shown = findings if args.show_baselined else new
        for f in shown:
            marker = "" if f in new else " [baselined]"
            print(f.format() + marker)
        if new:
            print(f"graftlint: {len(new)} new finding(s)"
                  + (f" ({len(known)} baselined)" if known else ""))
        elif known:
            print(f"graftlint: clean ({len(known)} baselined finding(s))")
        else:
            print("graftlint: clean")

    return EXIT_FINDINGS if new else EXIT_CLEAN


def main(argv=None) -> int:
    try:
        return run(argv)
    except SystemExit as e:  # argparse --help / bad flags
        code = e.code if isinstance(e.code, int) else EXIT_INTERNAL
        return EXIT_CLEAN if code == 0 else EXIT_INTERNAL
    except BrokenPipeError:
        # output truncated by a downstream `| head` — not an error; devnull
        # stdout so the interpreter's flush-at-exit doesn't re-raise
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return EXIT_CLEAN
    except Exception:
        traceback.print_exc()
        print("graftlint: internal error (exit 2)", file=sys.stderr)
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
