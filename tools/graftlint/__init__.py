"""graftlint: TPU/JAX-aware static analysis for the paddle_tpu tree.

The dispatch layer documents the failure modes that silently kill TPU
performance and distributed correctness — host syncs inside traces, retrace
storms, rank-conditional collectives that deadlock a slice — but until now
nothing enforced them. graftlint is the enforcement: an AST pass with a
pluggable rule registry (rules.py), a checked-in baseline so pre-existing
violations are tracked without blocking (baseline.py), and a runtime
cross-check mode (runtime.py) that validates the static reachability analysis
against actual host syncs observed through the framework's sync-observer hook.

Rules:
    GL001  host-sync-in-trace        .numpy()/float()/int()/bool()/`if t:`
                                     reachable from traced regions
    GL002  rank-conditional-collective  collective call under an `if rank`
                                     branch — static deadlock hazard
    GL003  swallowed-exception       `except Exception:` that neither logs
                                     nor re-raises
    GL004  retrace-hazard            mutable default args; Python-scalar
                                     defaults on jitted functions
    GL005  rng-key-reuse             same key passed to two random.* samplers
                                     without a split/reassignment

Suppress a finding in place with `# graftlint: disable=GL00N <reason>` on the
offending line. CLI: `python -m tools.graftlint paddle_tpu --baseline
tools/graftlint/baseline.json` (exit 0 clean / 1 new findings / 2 internal
error).
"""

from .engine import Finding, LintProject, lint_paths, load_project, run_rules
from .rules import RULES, Rule, get_rules

__all__ = [
    "Finding",
    "LintProject",
    "lint_paths",
    "load_project",
    "run_rules",
    "RULES",
    "Rule",
    "get_rules",
]
