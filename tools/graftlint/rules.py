"""Rule registry and the shipped rules (GL001-GL006).

Each rule is a singleton with an id, a one-line title, a rationale (shown by
`--list-rules` and docs/LINTING.md), and `check(project) -> Iterable[Finding]`.
Register new rules with `@register`; the CLI and tests pick them up through
`get_rules()`.

The analyses are deliberately syntactic over-approximations with documented
escape hatches (suppression comments, the baseline): on a 256-chip job the
cost asymmetry is extreme — a false positive costs one `# graftlint: disable=`
comment, a missed host sync or rank-conditional collective costs a hung slice.
"""

from __future__ import annotations

import ast
from collections import OrderedDict
from typing import Iterable, Iterator

from .engine import FileContext, Finding, LintProject

RULES: "OrderedDict[str, Rule]" = OrderedDict()


def register(cls):
    inst = cls()
    RULES[inst.id] = inst
    return cls


def get_rules(ids=None) -> list["Rule"]:
    if ids is None:
        return list(RULES.values())
    unknown = [i for i in ids if i not in RULES]
    if unknown:
        raise KeyError(f"unknown rule id(s): {', '.join(unknown)}")
    return [RULES[i] for i in ids]


class Rule:
    id: str = ""
    title: str = ""
    rationale: str = ""

    def check(self, project: LintProject) -> Iterable[Finding]:
        raise NotImplementedError


# --------------------------------------------------------------------------- #
# shared AST helpers
# --------------------------------------------------------------------------- #


def _call_name(func: ast.AST) -> str | None:
    """Simple name of a call target: `f(...)` -> "f", `a.b.f(...)` -> "f"."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _dotted_chain(node: ast.AST) -> list[str]:
    """`jax.random.normal` -> ["jax", "random", "normal"]; [] if not a pure
    name/attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _walk_skipping_defs(body: Iterable[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class bodies
    (those are analyzed as their own regions) or lambdas."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# --------------------------------------------------------------------------- #
# GL001 host-sync-in-trace
# --------------------------------------------------------------------------- #

# Entry points that put Python code under a jax trace: decorator names,
# wrapper calls whose function-valued arguments get traced, and the in-tree
# `with tracing_guard(True):` convention from framework/core.py.
_TRACE_DECORATORS = {"jit", "pjit", "to_static"}
_TRACE_TRANSFORMS = {
    "jit", "pjit", "to_static", "grad", "value_and_grad", "vjp", "jvp",
    "vmap", "pmap", "scan", "while_loop", "fori_loop", "cond", "checkpoint",
    "remat", "shard_map", "custom_vjp",
}
_HOST_SYNC_METHODS = {"numpy", "item", "tolist"}
_HOST_CASTS = {"float", "int", "bool"}
# Builtins whose result is a plain Python scalar even on tracers — casting
# them is not a device sync (false-positive guard: `float(len(xs))`).
_CAST_SAFE_CALLS = {"len", "ord", "hash", "round", "id"}


class _FnRecord:
    __slots__ = ("node", "ctx", "name", "qualname", "params", "calls",
                 "is_root", "guard_bodies", "scalar_defaults")

    def __init__(self, node, ctx, qualname):
        self.node = node
        self.ctx = ctx
        self.name = node.name
        self.qualname = qualname
        args = node.args
        self.params = {a.arg for a in
                       args.posonlyargs + args.args + args.kwonlyargs}
        self.calls: set[str] = set()
        self.is_root = False
        self.guard_bodies: list[list[ast.stmt]] = []
        self.scalar_defaults: list[tuple[str, ast.AST]] = []


def _decorator_marks_traced(dec: ast.AST) -> bool:
    """@jax.jit / @to_static / @functools.partial(jax.jit, ...) forms."""
    if isinstance(dec, ast.Call):
        name = _call_name(dec.func)
        if name in _TRACE_DECORATORS:
            return True
        if name == "partial" and dec.args:
            return _call_name(dec.args[0]) in _TRACE_DECORATORS
        return False
    return _call_name(dec) in _TRACE_DECORATORS


def _is_tracing_guard_with(node: ast.With) -> bool:
    return any(
        isinstance(item.context_expr, ast.Call)
        and _call_name(item.context_expr.func) == "tracing_guard"
        for item in node.items
    )


def _file_collectors(project: LintProject) -> list["_GL001Collector"]:
    """One AST collection pass per file per lint run, shared by GL001 and
    GL004 (memoized on the project — ~260 files would otherwise be walked
    once per consuming rule)."""
    cache = getattr(project, "_graftlint_fn_collectors", None)
    if cache is None:
        cache = []
        for ctx in project.files:
            col = _GL001Collector(ctx)
            col.visit(ctx.tree)
            cache.append(col)
        project._graftlint_fn_collectors = cache
    return cache


def _traced_records(project: LintProject):
    """Trace-reachability fixpoint shared by GL001 and GL006 (memoized on
    the project): returns (collectors, traced id set, traced _FnRecords).
    A record is traced when its function is decorated jit/to_static, passed
    to a jax transform, transitively called from either (same-file name
    matching — see HostSyncInTrace.rationale), or called from a
    `with tracing_guard(True):` body."""
    cache = getattr(project, "_graftlint_traced_records", None)
    if cache is not None:
        return cache
    collectors = _file_collectors(project)
    traced: set[int] = set()  # id(_FnRecord)
    traced_recs: list[_FnRecord] = []

    for col in collectors:
        by_name: dict[str, list[_FnRecord]] = {}
        for rec in col.fns:
            by_name.setdefault(rec.name, []).append(rec)
        worklist: list[str] = list(col.root_names)

        def guard_callees(rec: _FnRecord, _wl=worklist):
            for body in rec.guard_bodies:
                for node in _walk_skipping_defs(body):
                    if isinstance(node, ast.Call):
                        n = _call_name(node.func)
                        if n:
                            _wl.append(n)

        def mark(rec: _FnRecord, _wl=worklist):
            if id(rec) in traced:
                return
            traced.add(id(rec))
            traced_recs.append(rec)
            _wl.extend(rec.calls)
            guard_callees(rec)

        for rec in col.fns:
            if rec.is_root:
                mark(rec)
            else:
                # a tracing_guard body is traced even when its enclosing
                # function is not — seed its callees
                guard_callees(rec)

        while worklist:
            name = worklist.pop()
            for rec in by_name.get(name, []):
                mark(rec)

    cache = (collectors, traced, traced_recs)
    project._graftlint_traced_records = cache
    return cache


class _GL001Collector(ast.NodeVisitor):
    """Per-file pass: function records, call edges, trace roots."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.stack: list[_FnRecord] = []
        self.fns: list[_FnRecord] = []
        self.root_names: set[str] = set()

    def _visit_fn(self, node):
        qual = ".".join([f.name for f in self.stack] + [node.name])
        rec = _FnRecord(node, self.ctx, qual)
        rec.is_root = any(_decorator_marks_traced(d) for d in node.decorator_list)
        args = node.args
        pos = args.posonlyargs + args.args
        for a, d in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            rec.scalar_defaults.append((a.arg, d))
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None:
                rec.scalar_defaults.append((a.arg, d))
        self.fns.append(rec)
        self.stack.append(rec)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With):
        if self.stack and _is_tracing_guard_with(node):
            self.stack[-1].guard_bodies.append(node.body)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        name = _call_name(node.func)
        if self.stack and name is not None:
            if isinstance(node.func, ast.Name):
                self.stack[-1].calls.add(name)
            elif (isinstance(node.func, ast.Attribute)
                  and isinstance(node.func.value, ast.Name)
                  and node.func.value.id in ("self", "cls")):
                self.stack[-1].calls.add(name)
        # `jax.jit(step)`, `jax.value_and_grad(loss_fn)`, `jax.lax.scan(body,…)`
        # and `functools.partial(jax.jit, ...)(fn)`: function-valued args get
        # traced when the wrapper runs
        if name in _TRACE_TRANSFORMS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.root_names.add(arg.id)
        self.generic_visit(node)


@register
class HostSyncInTrace(Rule):
    id = "GL001"
    title = "host sync reachable from a traced region"
    rationale = (
        "Inside jax tracing, .numpy()/.item()/.tolist(), float()/int()/bool() "
        "casts, and `if tensor:` force the tracer to concretize — at best a "
        "TracerArrayConversionError, at worst (through a fallback path) a "
        "device-to-host round trip per step that serializes the TPU pipeline. "
        "Reachability: functions decorated with jit/to_static, functions "
        "passed to jax transforms, bodies of `with tracing_guard(True):`, "
        "plus everything they transitively call. Call edges are matched by "
        "simple name *within the defining file* — cross-file matching on "
        "names like `step`/`fn`/`update` drowned true positives in "
        "collisions; helpers traced from another module belong in that "
        "module's own trace roots."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        collectors, traced, traced_recs = _traced_records(project)

        seen: set[tuple[str, int, str]] = set()
        findings: list[Finding] = []

        def emit(ctx, node, msg):
            f = ctx.finding(self.id, node, msg)
            key = (f.path, f.line, msg)
            if key not in seen:
                seen.add(key)
                findings.append(f)

        def scan_region(ctx: FileContext, body, params: set[str], where: str):
            # `params` is non-empty only for directly-jitted functions: their
            # positional args ARE tracers. Transitively-traced helpers often
            # take Python config values (flags, axis ints) where `if flag:`
            # is a legitimate static branch.
            for node in _walk_skipping_defs(body):
                if isinstance(node, ast.Call):
                    if (isinstance(node.func, ast.Attribute)
                            and node.func.attr in _HOST_SYNC_METHODS):
                        emit(ctx, node,
                             f"`.{node.func.attr}()` is a host sync but is "
                             f"reachable under tracing via {where}")
                    elif (isinstance(node.func, ast.Name)
                          and node.func.id in _HOST_CASTS
                          and len(node.args) == 1 and not node.keywords):
                        arg = node.args[0]
                        if isinstance(arg, ast.Constant):
                            continue
                        if (isinstance(arg, ast.Call)
                                and _call_name(arg.func) in _CAST_SAFE_CALLS):
                            continue
                        emit(ctx, node,
                             f"`{node.func.id}()` concretizes its argument "
                             f"under tracing (reached via {where})")
                elif isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    if isinstance(test, ast.Name) and test.id in params:
                        emit(ctx, test,
                             f"`if {test.id}:` on a traced-function parameter "
                             "forces a concrete bool under tracing "
                             f"(reached via {where})")

        for rec in traced_recs:
            scan_region(rec.ctx, rec.node.body,
                        rec.params if rec.is_root else set(),
                        f"traced function `{rec.qualname}`")
        # guard bodies inside non-traced functions still execute under trace
        for col in collectors:
            for rec in col.fns:
                if id(rec) in traced:
                    continue
                for body in rec.guard_bodies:
                    scan_region(rec.ctx, body, set(),
                                f"`with tracing_guard(...)` in `{rec.qualname}`")
        return findings


# --------------------------------------------------------------------------- #
# GL002 rank-conditional collective
# --------------------------------------------------------------------------- #

# Unambiguous collective entry points (paddle_tpu.distributed.collective and
# eager_multiproc): every rank in the group must reach the call site.
_COLLECTIVES = {
    "all_reduce", "all_gather", "all_gather_object", "reduce_scatter",
    "alltoall", "alltoall_single", "broadcast_object_list",
    "scatter_object_list", "allreduce_value", "allgather_values",
    "allgather_objects", "broadcast_value", "broadcast_objects",
    "store_allreduce_group", "sync_global_devices",
    # MoE expert dispatch (distributed/utils/moe_utils.py): every rank must
    # reach the exchange even when ITS per-rank expert counts are zero —
    # count-gated calls are the canonical expert-parallel deadlock
    "global_scatter", "global_gather",
}
# Names that are collectives only in dotted form (`dist.reduce(...)`); the
# bare names collide with builtins/stdlib (functools.reduce, Event.wait).
_COLLECTIVES_DOTTED_ONLY = {"reduce", "scatter", "broadcast", "barrier"}
_RANK_NAMES = {"rank", "local_rank", "global_rank", "rank_id"}
_RANK_CALLS = {"get_rank", "process_index", "get_group_rank", "local_rank"}


def _mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in _RANK_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _RANK_NAMES:
            return True
        if isinstance(node, ast.Call) and _call_name(node.func) in _RANK_CALLS:
            return True
    return False


def _is_collective_call(node: ast.Call) -> bool:
    name = _call_name(node.func)
    if name in _COLLECTIVES:
        return True
    return (name in _COLLECTIVES_DOTTED_ONLY
            and isinstance(node.func, ast.Attribute))


@register
class RankConditionalCollective(Rule):
    id = "GL002"
    title = "collective call under a rank-conditional branch"
    rationale = (
        "A collective reached by only a subset of ranks deadlocks the group: "
        "participating chips park in the all-reduce while the excluded rank "
        "never arrives, and the job hangs with no error until the comm "
        "watchdog (or the operator) kills it. Branching on rank is fine for "
        "logging or p2p send/recv — but group collectives must be reached "
        "unconditionally by every member."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        for ctx in project.files:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.If) or not _mentions_rank(node.test):
                    continue
                # a nested `if rank` is visited by ast.walk on its own — stop
                # at it here so each call site is reported exactly once,
                # against its nearest rank-conditional
                for sub in self._iter_branch(node.body + node.orelse):
                    if isinstance(sub, ast.Call) and _is_collective_call(sub):
                        yield ctx.finding(
                            self.id, sub,
                            f"collective `{_call_name(sub.func)}` inside a "
                            "rank-conditional branch — ranks that skip the "
                            "branch never join and the group deadlocks")

    @classmethod
    def _iter_branch(cls, nodes) -> Iterator[ast.AST]:
        stack = list(nodes)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(node, ast.If) and _mentions_rank(node.test):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------- #
# GL003 swallowed exception
# --------------------------------------------------------------------------- #

_BROAD_EXC = {"Exception", "BaseException"}
# Handlers inside these functions are exempt: raising out of GC/teardown is
# worse than the swallow (store.py __del__ is the canonical case).
_GL003_ALLOWLIST_FUNCS = {"__del__"}


@register
class SwallowedException(Rule):
    id = "GL003"
    title = "broad exception handler that neither logs nor re-raises"
    rationale = (
        "`except Exception: pass` turns real faults — a dead TCPStore, a "
        "poisoned collective, a corrupt checkpoint shard — into silent "
        "no-ops; PR 1's resilience machinery can only recover from faults it "
        "can observe. A broad handler must log, re-raise, or carry an "
        "explicit `# graftlint: disable=GL003 <reason>`."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        for ctx in project.files:
            allowed_spans: list[tuple[int, int]] = []
            for node in ast.walk(ctx.tree):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node.name in _GL003_ALLOWLIST_FUNCS):
                    allowed_spans.append((node.lineno, node.end_lineno or node.lineno))
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not self._is_broad(node.type):
                    continue
                if any(lo <= node.lineno <= hi for lo, hi in allowed_spans):
                    continue
                if self._handles(node.body):
                    continue
                caught = "bare `except:`" if node.type is None else \
                    f"`except {ast.unparse(node.type)}:`"
                yield ctx.finding(
                    self.id, node,
                    f"{caught} swallows the error without logging or "
                    "re-raising — narrow the type, log it, or add "
                    "`# graftlint: disable=GL003 <reason>`")

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True
        names = ([type_node] if not isinstance(type_node, ast.Tuple)
                 else list(type_node.elts))
        return any(_call_name(n) in _BROAD_EXC for n in names)

    @staticmethod
    def _handles(body) -> bool:
        """A handler 'handles' if it raises or makes any call (logging,
        cleanup, metric bump) — pure pass/continue/return/assignment does not."""
        for node in _walk_skipping_defs(body):
            if isinstance(node, (ast.Raise, ast.Call)):
                return True
        return False


# --------------------------------------------------------------------------- #
# GL004 retrace hazard
# --------------------------------------------------------------------------- #


@register
class RetraceHazard(Rule):
    id = "GL004"
    title = "argument pattern that defeats the dispatch/trace cache"
    rationale = (
        "The eager dispatch cache (framework/core.py) keys on the *values* "
        "of defaults and closures: a mutable default ({}, []) either fails "
        "to hash (permanent eager bypass — per-call retrace) or churns the "
        "key every time it is mutated. On jitted entry points, a Python "
        "int/float default is baked per *value*: each new scalar is a fresh "
        "trace — the weak-type retrace storm core.py:657-821 documents."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        for col in _file_collectors(project):
            ctx = col.ctx
            for rec in col.fns:
                for name, default in rec.scalar_defaults:
                    if self._is_mutable(default):
                        yield ctx.finding(
                            self.id, default,
                            f"mutable default for `{name}` in "
                            f"`{rec.qualname}` — unhashable in dispatch-cache "
                            "keys (permanent per-call retrace) and shared "
                            "across calls")
                    elif rec.is_root and isinstance(default, ast.Constant) \
                            and type(default.value) in (int, float):
                        yield ctx.finding(
                            self.id, default,
                            f"Python scalar default `{name}={default.value!r}` "
                            f"on jitted `{rec.qualname}` — every distinct "
                            "value passed at a call site triggers a retrace; "
                            "make it a static arg or close over it")

    @staticmethod
    def _is_mutable(node: ast.AST) -> bool:
        if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                             ast.DictComp, ast.SetComp)):
            return True
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("list", "dict", "set", "bytearray"))


# --------------------------------------------------------------------------- #
# GL005 RNG key reuse
# --------------------------------------------------------------------------- #

_SAMPLERS = {
    "normal", "uniform", "randint", "bernoulli", "categorical", "gumbel",
    "truncated_normal", "permutation", "choice", "bits", "exponential",
    "laplace", "poisson", "rademacher", "beta", "gamma", "dirichlet",
}
# numpy's stateful API shares sampler names but takes loc/scale, not keys
_NON_KEYED_ROOTS = {"np", "numpy"}


def _sampler_key_arg(node: ast.Call):
    """Return the Name node of the key argument if this is a keyed sampler."""
    if not isinstance(node.func, ast.Attribute):
        return None
    if node.func.attr not in _SAMPLERS:
        return None
    chain = _dotted_chain(node.func)
    if chain and chain[0] in _NON_KEYED_ROOTS:
        return None
    if "random" not in chain[:-1] and not any(
            kw.arg == "key" for kw in node.keywords):
        return None
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value
    if node.args and isinstance(node.args[0], ast.Name):
        return node.args[0]
    return None


def _assigned_names(stmt: ast.stmt) -> set[str]:
    out: set[str] = set()

    def targets(t):
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            targets(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                targets(item.optional_vars)
    # walrus anywhere inside the statement
    for node in ast.walk(stmt):
        if isinstance(node, ast.NamedExpr):
            targets(node.target)
    return out


def _terminates(stmts: list) -> bool:
    """Block ends on a statement control flow cannot fall out of."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


@register
class RngKeyReuse(Rule):
    id = "GL005"
    title = "same RNG key consumed by two sampler calls"
    rationale = (
        "jax PRNG keys are pure values: passing one key to two random.* "
        "samplers yields *identical* randomness — correlated dropout masks, "
        "duplicated init noise — silently. Every consumption must be "
        "preceded by a fresh `split` (or fold_in), i.e. a reassignment of "
        "the key variable."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        for ctx in project.files:
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._scan_block(ctx, node.body, {}, set())[2]

    def _scan_block(self, ctx, body, used: dict, assigned: set):
        """Sequential scan. Returns (used, assigned, findings); `used` maps
        key-var name -> line of its consuming use."""
        findings: list[Finding] = []
        used = dict(used)
        assigned = set(assigned)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                u1, a1, f1 = self._scan_block(ctx, stmt.body, used, assigned)
                u2, a2, f2 = self._scan_block(ctx, stmt.orelse, used, assigned)
                findings += f1 + f2
                # exclusive branches: a use in one arm does not collide with
                # the other; later code collides only with arms that can
                # fall through (a `return`ing arm never reaches it)
                if _terminates(stmt.body):
                    u1, a1 = used, set()
                if stmt.orelse and _terminates(stmt.orelse):
                    u2, a2 = used, set()
                used = {**u1, **u2}
                assigned |= a1 | a2
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                loop_assigned = _assigned_names(stmt)
                for s in stmt.body:
                    loop_assigned |= _assigned_names(s)
                u1, a1, f1 = self._scan_block(ctx, stmt.body, used,
                                              assigned | loop_assigned)
                findings += f1
                # a key consumed in the body but never reassigned inside the
                # loop is reused verbatim on every iteration
                for name, line in u1.items():
                    if name not in loop_assigned and name not in used:
                        findings.append(Finding(
                            self.id, ctx.rel_path, line, 0,
                            f"key `{name}` is consumed inside a loop without "
                            "being split/reassigned per iteration — every "
                            "pass replays the same randomness",
                            ctx.snippet_at(line)))
                used.update(u1)
                assigned |= a1 | loop_assigned
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                # context expressions evaluate first, then the body runs
                # sequentially — flattening the whole With as one statement
                # would see body samplers before body reassignments
                for item in stmt.items:
                    self._consume_samplers(ctx, item.context_expr, used,
                                           findings)
                for name in _assigned_names(stmt):
                    used.pop(name, None)
                    assigned.add(name)
                used, a1, f1 = self._scan_block(ctx, stmt.body, used, assigned)
                findings += f1
                assigned |= a1
                continue
            if isinstance(stmt, ast.Try):
                u1, a1, f1 = self._scan_block(ctx, stmt.body, used, assigned)
                findings += f1
                for h in stmt.handlers:
                    u2, a2, f2 = self._scan_block(ctx, h.body, used, assigned)
                    findings += f2
                    u1.update(u2)
                    a1 |= a2
                u3, a3, f3 = self._scan_block(
                    ctx, stmt.orelse + stmt.finalbody, u1, assigned | a1)
                findings += f3
                used, assigned = u3, assigned | a1 | a3
                continue

            # plain statement: find sampler uses in document order, then
            # apply this statement's assignments (`k2 = normal(k2, …)` is
            # use-then-assign: the read happens before the rebind)
            self._consume_samplers(ctx, stmt, used, findings)
            for name in _assigned_names(stmt):
                used.pop(name, None)
                assigned.add(name)
        return used, assigned, findings

    def _consume_samplers(self, ctx, node, used: dict, findings: list):
        """Record/flag every keyed sampler call under `node` in source order."""
        calls = [n for n in _walk_skipping_defs([node])
                 if isinstance(n, ast.Call)]
        calls.sort(key=lambda n: (n.lineno, n.col_offset))
        for call in calls:
            key_arg = _sampler_key_arg(call)
            if key_arg is None:
                continue
            name = key_arg.id
            if name in used:
                findings.append(Finding(
                    self.id, ctx.rel_path, key_arg.lineno, key_arg.col_offset,
                    f"key `{name}` already consumed by a sampler on line "
                    f"{used[name]} — split it (`k1, k2 = split({name})`) "
                    "before sampling again", ctx.snippet_at(key_arg.lineno)))
            else:
                used[name] = key_arg.lineno


# --------------------------------------------------------------------------- #
# GL006 unlabeled hot-path metric
# --------------------------------------------------------------------------- #

# Unambiguous emission verbs of the observability metrics API
# (paddle_tpu/observability/metrics.py Counter.inc / Histogram.observe).
_METRIC_EMIT_ALWAYS = {"inc", "observe"}
# Verbs that collide with stdlib names (set()/dict.add): flagged only when
# the receiver chain reads metric-ish.
_METRIC_EMIT_GUARDED = {"set", "add", "dec"}
_METRICISH_HINTS = ("metric", "counter", "gauge", "hist")


def _metricish_receiver(func: ast.Attribute) -> bool:
    chain = _dotted_chain(func)
    return any(h in part.lower() for part in chain[:-1] for h in _METRICISH_HINTS)


@register
class HotPathMetric(Rule):
    id = "GL006"
    title = "unlabeled hot-path metric: emission inside a traced region"
    rationale = (
        "A metric emitted from inside a jit/to_static trace only executes "
        "via a host callback — XLA must round-trip to Python every step, "
        "serializing the TPU pipeline exactly like a host sync (and under "
        "plain tracing it silently runs once at trace time, recording "
        "nothing). Accumulate on-device and emit at the step boundary "
        "(`StepTimeline.step_end` / the fit loop), or pre-bind the labeled "
        "cell outside the trace. Reachability matches GL001: jit/to_static "
        "decorators, jax-transform arguments, tracing_guard bodies, and "
        "their same-file transitive callees."
    )

    def check(self, project: LintProject) -> Iterable[Finding]:
        collectors, traced, traced_recs = _traced_records(project)

        def scan_region(ctx: FileContext, body, where: str):
            for node in _walk_skipping_defs(body):
                if not isinstance(node, ast.Call) or \
                        not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr in _METRIC_EMIT_ALWAYS or (
                        attr in _METRIC_EMIT_GUARDED
                        and _metricish_receiver(node.func)):
                    yield ctx.finding(
                        self.id, node,
                        f"metric emission `.{attr}()` is reachable under "
                        f"tracing via {where} — a per-step host callback; "
                        "accumulate on-device and emit at the step boundary")

        for rec in traced_recs:
            yield from scan_region(rec.ctx, rec.node.body,
                                   f"traced function `{rec.qualname}`")
        for col in collectors:
            for rec in col.fns:
                if id(rec) in traced:
                    continue
                for body in rec.guard_bodies:
                    yield from scan_region(
                        rec.ctx, body,
                        f"`with tracing_guard(...)` in `{rec.qualname}`")
