"""Lint engine: file discovery, parsing, suppression comments, rule driving.

The engine is deliberately framework-agnostic: it knows nothing about jax or
paddle_tpu. Rules (rules.py) receive a `LintProject` — every parsed file plus
cheap cross-file indexes — and yield `Finding`s; the engine filters the ones
suppressed by `# graftlint: disable=RULE` comments and orders the rest.

A finding's identity for baseline purposes is (path, rule, source-line text),
not the line *number* — unrelated edits above a tracked violation must not
invalidate the baseline (see baseline.py).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Sequence

# `# graftlint: disable=GL001` or `disable=GL001,GL003 free-text reason`
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
)

# Rule id for files the engine itself cannot analyze (syntax errors): always
# reported, never suppressible, so a truncated checkout fails loudly.
PARSE_ERROR_RULE = "GL000"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix path relative to the lint root
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline (see module doc)."""
        return f"{self.rule}|{self.path}|{self.snippet}"


@dataclass
class FileContext:
    """One parsed source file."""

    path: Path
    rel_path: str
    source: str
    lines: list[str]
    tree: ast.AST
    # line number -> set of suppressed rule ids ("all" suppresses everything)
    suppressions: dict[int, set[str]] = field(default_factory=dict)

    def snippet_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()[:160]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.rel_path, line, col, message,
                       self.snippet_at(line))

    def is_suppressed(self, f: Finding) -> bool:
        sup = self.suppressions.get(f.line)
        return bool(sup) and (f.rule in sup or "all" in sup)


@dataclass
class LintProject:
    root: Path
    files: list[FileContext]
    parse_errors: list[Finding] = field(default_factory=list)

    def by_rel_path(self, rel_path: str) -> FileContext | None:
        for ctx in self.files:
            if ctx.rel_path == rel_path:
                return ctx
        return None


def _parse_suppressions(lines: Sequence[str]) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for i, text in enumerate(lines, start=1):
        if "graftlint" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
    return out


def iter_py_files(paths: Iterable[Path]) -> list[Path]:
    seen: dict[Path, None] = {}
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                seen.setdefault(f.resolve(), None)
        elif p.is_file():
            seen.setdefault(p.resolve(), None)
        else:
            # a *missing* path is caller error (CLI exit 2), distinct from an
            # existing-but-unparsable file (a GL000 finding, exit 1)
            raise FileNotFoundError(f"graftlint: no such file or directory: {p}")
    return list(seen)


def load_project(paths: Sequence[Path | str], root: Path | str | None = None) -> LintProject:
    root = Path(root) if root is not None else Path.cwd()
    root = root.resolve()
    files: list[FileContext] = []
    parse_errors: list[Finding] = []
    for f in iter_py_files([Path(p) for p in paths]):
        try:
            rel = f.relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            source = f.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=str(f))
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 1) or 1
            parse_errors.append(Finding(
                PARSE_ERROR_RULE, rel, line, 0,
                f"file could not be parsed: {e.__class__.__name__}: {e}"))
            continue
        lines = source.splitlines()
        files.append(FileContext(
            path=f, rel_path=rel, source=source, lines=lines, tree=tree,
            suppressions=_parse_suppressions(lines)))
    return LintProject(root=root, files=files, parse_errors=parse_errors)


def run_rules(project: LintProject, rules=None) -> list[Finding]:
    """Run rules over the project; drop suppressed findings; stable order."""
    from .rules import get_rules

    ctx_by_path = {ctx.rel_path: ctx for ctx in project.files}
    findings: list[Finding] = list(project.parse_errors)
    for rule in get_rules(rules):
        for f in rule.check(project):
            ctx = ctx_by_path.get(f.path)
            if ctx is not None and ctx.is_suppressed(f):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(paths: Sequence[Path | str], root: Path | str | None = None,
               rules=None) -> list[Finding]:
    """One-call API used by the tests: discover, parse, run, filter."""
    return run_rules(load_project(paths, root=root), rules=rules)
