#!/usr/bin/env python
"""ops.yaml name-resolution audit (DESIGN_DECISIONS.md §ops-audit).

Probes every `- op:` name in the reference's ops.yaml against the public
namespaces plus the _C_ops kernel surface. Prints the resolution ratio and
any unresolved names (expected: exactly the 11 recorded scope-outs).
"""

import re
import sys

OPS_YAML = "/root/reference/paddle/phi/ops/yaml/ops.yaml"

SCOPE_OUTS = {
    "batch_fc", "cvm", "match_matrix_tensor", "pyramid_hash",
    "rank_attention", "shuffle_batch", "tdm_child", "tdm_sampler",
    "dgc", "dgc_clip_by_norm", "dgc_momentum",
}


def main():
    names = []
    for line in open(OPS_YAML):
        m = re.match(r"- op\s*:\s*(\w+)", line)
        if m:
            names.append(m.group(1))

    import paddle_tpu as paddle
    import paddle_tpu._C_ops as C
    import paddle_tpu.incubate.nn.functional as IF
    import paddle_tpu.nn.functional as F

    namespaces = [
        paddle, paddle.Tensor, F, C, IF, paddle.linalg, paddle.fft,
        paddle.signal, paddle.sparse, paddle.incubate, paddle.geometric,
        paddle.vision, paddle.vision.ops, paddle.nn, paddle.nn.quant,
        paddle.nn.utils, paddle.distributed, paddle.metric, paddle.text,
        paddle.static, paddle.amp, paddle.distribution,
    ]

    def resolves(n):
        cands = [n, n[:-1]] if n.endswith("_") else [n]
        return any(hasattr(ns, c) for c in cands for ns in namespaces)

    unresolved = [n for n in names if not resolves(n)]
    pct = 100.0 * (1 - len(unresolved) / len(names))
    print(f"ops.yaml names: {len(names)}  unresolved: {len(unresolved)}  "
          f"resolution: {pct:.1f}%")
    unexpected = [n for n in unresolved if n not in SCOPE_OUTS]
    for n in unresolved:
        tag = "" if n in SCOPE_OUTS else "  <-- NOT scope-recorded"
        print(f"  {n}{tag}")
    return 1 if unexpected else 0


if __name__ == "__main__":
    sys.exit(main())
